"""Fused optimizer: bucketed single-pass updates + quantized resident
moments.

The plain step leaves the optimizer to optax: a long chain of
per-leaf elementwise HLOs, with fp32 moments dominating resident state,
checkpoint bytes and p2p migration bytes. This module is the raw-speed
variant (ROADMAP item 4): parameters/gradients are packed into the SAME
flat dtype-grouped buckets as the DCN gradient path
(train/comm.plan_buckets, align = the 128 TPU lane width) and each
bucket's whole update — momentum-SGD or Adam(W), optionally with the
moments dequantized-updated-requantized in place — runs as ONE Pallas
VMEM pass (ops/opt_kernels.py; plain-XLA expression everywhere off-TPU,
bitwise-identical by construction).

Resident moment formats (``quant``):

- ``off``: fp32 bucket buffers. The fused fp32 momentum-SGD update is
  BITWISE-identical to optax.chain(add_decayed_weights, sgd(momentum))
  + apply_updates (pinned by ``update_parity_gate`` and CI); Adam
  matches optax.adamw to float tolerance (bias-correction pow order).
- ``int8``/``fp8``: each moment plane lives between steps as
  (q, scale, rq, rscale) — the quantized moment plus its quantized
  error-feedback RESIDUAL (ops/opt_kernels.QPlane). 2 bytes/element vs
  fp32's 4: optimizer state, checkpoint bytes and migration
  donor-manifest bytes halve, and elastic peer restores ship half the
  moment bytes. Behind the r21 gate discipline: the quantized path
  must keep >= 1-envelope of the dense run's loss improvement on the
  CNN + transformer convergence smokes (``convergence_smoke``,
  ``python -m edl_tpu.train.fused_opt smoke`` in CI).

Integration: ``FusedOptimizer`` is duck-typed where optax's
GradientTransformation sits (``TrainState.create(tx=fused_sgd(...))``);
``TrainState.apply_gradients`` routes through ``fused_apply`` whenever
the tx provides it, so the plain jit step, the amp step and the
comm-path step all pick it up without changes.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.ops import opt_kernels as ok
from edl_tpu.train import comm as comm_lib

OPTIMIZERS = ok.OPTIMIZERS
QUANT_MODES = ok.QUANT_MODES
FUSED_MODES = ("off", "fp32", "int8", "fp8")   # the --fused-opt knob

_LANE = 128

ScheduleOrFloat = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class FusedOptState(NamedTuple):
    """Resident optimizer state: per-bucket moment buffers.

    count: int32 step counter (Adam bias correction; schedule input).
    m: per-bucket first moments — fp32 buffers (quant='off') or
       ops.opt_kernels.QPlane quadruples.
    v: per-bucket second moments (Adam only; () for momentum-SGD).
    """

    count: jnp.ndarray
    m: tuple
    v: tuple


class FusedOptimizer:
    """Bucketed fused optimizer with optax-compatible ``init``.

    Not an optax.GradientTransformation: the fused path has no
    "updates tree" intermediate (the param write happens inside the
    kernel pass), so instead of ``update`` it exposes
    ``fused_apply(grads, opt_state, params) -> (new_params,
    new_opt_state)`` — the hook TrainState.apply_gradients dispatches
    on. ``update`` raises with that pointer rather than silently
    de-fusing.
    """

    def __init__(self, optimizer: str, learning_rate: ScheduleOrFloat,
                 *, momentum: float = 0.9, b1: float = 0.9,
                 b2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0, quant: str = "off",
                 bucket_mb: float = 4.0):
        if optimizer not in OPTIMIZERS:
            raise ValueError(f"optimizer must be one of {OPTIMIZERS}, "
                             f"got {optimizer!r}")
        if quant not in QUANT_MODES:
            raise ValueError(f"quant must be one of {QUANT_MODES}, "
                             f"got {quant!r}")
        if quant == "fp8" and ok.fp8_dtype() is None:
            raise ValueError("quant='fp8' needs a jax build with "
                             "float8_e4m3fn; use quant='int8'")
        if (optimizer == "adam" and quant != "off"
                and ok.fp8_dtype() is None):
            raise ValueError(
                "quantized Adam needs a jax build with float8_e4m3fn: "
                "the second moment always rides the fp8 codec "
                "(ops/opt_kernels.V_QUANT — a linear int8 grid under "
                "the update's sqrt denominator explodes)")
        if bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
        self.optimizer = optimizer
        self.learning_rate = learning_rate
        self.momentum = float(momentum)
        self.b1, self.b2, self.eps = float(b1), float(b2), float(eps)
        self.weight_decay = float(weight_decay)
        self.quant = quant
        self.bucket_mb = float(bucket_mb)

    # plan is a pure function of leaf shapes/dtypes (deterministic —
    # the same seeded-exact contract as the comm path), so recomputing
    # per call is safe; calls happen at trace time only.
    def plan(self, params) -> comm_lib.BucketPlan:
        plan = comm_lib.plan_buckets(params, self.bucket_mb,
                                     align=_LANE)
        for b in plan.buckets:
            if not jnp.issubdtype(b.dtype, jnp.floating):
                raise ValueError(
                    f"fused optimizer supports float params only; got "
                    f"a {b.dtype} bucket")
        return plan

    def init(self, params) -> FusedOptState:
        plan = self.plan(params)

        def zero(b):
            if self.quant == "off":
                return jnp.zeros((b.padded,), jnp.float32)
            return ok.zero_plane(b.padded, self.quant)

        m = tuple(zero(b) for b in plan.buckets)
        v = (tuple(zero(b) for b in plan.buckets)
             if self.optimizer == "adam" else ())
        return FusedOptState(count=jnp.zeros((), jnp.int32), m=m, v=v)

    def update(self, grads, state, params=None):
        raise NotImplementedError(
            "FusedOptimizer has no de-fused update(); the param write "
            "happens inside the kernel pass. Use fused_apply(grads, "
            "opt_state, params) — TrainState.apply_gradients does so "
            "automatically.")

    def fused_apply(self, grads, opt_state: FusedOptState, params):
        """One fused optimizer step over every bucket.

        Returns (new_params, new_opt_state). Traceable — runs inside
        the jitted train step.
        """
        plan = self.plan(params)
        p_bufs = comm_lib.pack_buckets(params, plan)
        g_bufs = comm_lib.pack_buckets(grads, plan)
        lr = (self.learning_rate(opt_state.count)
              if callable(self.learning_rate) else self.learning_rate)
        lr = jnp.asarray(lr, jnp.float32)
        if self.optimizer == "adam":
            t = (opt_state.count + 1).astype(jnp.float32)
            c1 = 1.0 - jnp.asarray(self.b1, jnp.float32) ** t
            c2 = 1.0 - jnp.asarray(self.b2, jnp.float32) ** t
        new_p, new_m, new_v = [], [], []
        for i, b in enumerate(plan.buckets):
            p = p_bufs[i].astype(jnp.float32)
            g = g_bufs[i].astype(jnp.float32)
            if self.optimizer == "sgdm":
                pn, mn = ok.sgdm_bucket(
                    p, g, opt_state.m[i], lr, mu=self.momentum,
                    wd=self.weight_decay, quant=self.quant)
            else:
                pn, mn, vn = ok.adam_bucket(
                    p, g, opt_state.m[i], opt_state.v[i], lr, c1, c2,
                    b1=self.b1, b2=self.b2, eps=self.eps,
                    wd=self.weight_decay, quant=self.quant)
                new_v.append(vn)
            new_p.append(pn.astype(b.dtype))
            new_m.append(mn)
        new_params = comm_lib.unpack_buckets(new_p, plan)
        return new_params, FusedOptState(count=opt_state.count + 1,
                                         m=tuple(new_m),
                                         v=tuple(new_v))


def fused_sgd(learning_rate: ScheduleOrFloat, momentum: float = 0.9,
              weight_decay: float = 0.0, *, quant: str = "off",
              bucket_mb: float = 4.0) -> FusedOptimizer:
    """Fused momentum-SGD; fp32 mode is bitwise vs
    optax.chain(add_decayed_weights(wd), sgd(lr, momentum))."""
    return FusedOptimizer("sgdm", learning_rate, momentum=momentum,
                          weight_decay=weight_decay, quant=quant,
                          bucket_mb=bucket_mb)


def fused_adam(learning_rate: ScheduleOrFloat, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8,
               weight_decay: float = 0.0, *, quant: str = "off",
               bucket_mb: float = 4.0) -> FusedOptimizer:
    """Fused Adam(W); matches optax.adamw (eps_root=0) to float
    tolerance in fp32 mode."""
    return FusedOptimizer("adam", learning_rate, b1=b1, b2=b2, eps=eps,
                          weight_decay=weight_decay, quant=quant,
                          bucket_mb=bucket_mb)


def make_fused_tx(optimizer: str, learning_rate: ScheduleOrFloat,
                  fused_mode: str, **kw):
    """The --fused-opt knob -> tx. fused_mode: off|fp32|int8|fp8
    ('off' returns None — caller keeps its optax chain)."""
    if fused_mode not in FUSED_MODES:
        raise ValueError(f"fused mode must be one of {FUSED_MODES}, "
                         f"got {fused_mode!r}")
    if fused_mode == "off":
        return None
    quant = "off" if fused_mode == "fp32" else fused_mode
    factory = fused_sgd if optimizer == "sgdm" else fused_adam
    return factory(learning_rate, quant=quant, **kw)


def opt_state_bytes(opt_state) -> int:
    """Resident optimizer-state bytes (sum over leaves) — the metric
    the quantized modes must cut >= 1.8x."""
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               if hasattr(l, "shape") else np.asarray(l).nbytes
               for l in jax.tree.leaves(opt_state))


# -- parity gate -------------------------------------------------------------


def _gate_world(seed: int = 0):
    """A small ragged param/grad tree exercising multi-bucket packing,
    lane padding and the oversized-leaf path."""
    rng = np.random.default_rng(seed)

    def leaf(*shape):
        return jnp.asarray(rng.normal(0, 0.1, size=shape)
                           .astype(np.float32))

    params = {"dense": {"kernel": leaf(257, 33), "bias": leaf(33)},
              "emb": leaf(64, 64), "norm": {"scale": leaf(129)}}
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.normal(0, 0.02, size=p.shape)
                              .astype(np.float32)), params)
    return params, grads


def _run_fused(tx: FusedOptimizer, params, grads, steps: int):
    state = tx.init(params)
    for _ in range(steps):
        params, state = tx.fused_apply(grads, state, params)
    return params, state


def update_parity_gate(seed: int = 0, steps: int = 3,
                       lr: float = 0.1, wd: float = 1e-4) -> dict:
    """The fused path's equivalence gate (CI runs it in `smoke`).

    - fused-fp32 momentum-SGD is BITWISE-identical to the optax chain;
    - fused-fp32 Adam matches optax.adamw within float tolerance;
    - for every optimizer x quant mode, the interpret-mode Pallas
      kernel is BITWISE-identical to the plain-XLA fallback (the same
      jnp math on both sides — this is the structural guarantee the
      TPU path inherits).
    """
    import optax

    params, grads = _gate_world(seed)
    report: dict = {"steps": steps}

    def optax_run(tx):
        # jitted like the fused path, so XLA's fusion (fma contraction)
        # is identical on both sides of the bitwise comparison
        @jax.jit
        def one(p, s):
            u, s = tx.update(grads, s, p)
            return optax.apply_updates(p, u), s

        p, s = params, tx.init(params)
        for _ in range(steps):
            p, s = one(p, s)
        return p

    def kernel_vs_xla(tx):
        p_xla, s_xla = _run_fused(tx, params, grads, steps)
        prev = ok._FORCE_INTERPRET
        ok.force_pallas_interpret()
        try:
            p_krn, s_krn = _run_fused(tx, params, grads, steps)
        finally:
            ok._FORCE_INTERPRET = prev
        return (comm_lib.tree_bitwise_equal(p_xla, p_krn)
                and comm_lib.tree_bitwise_equal(s_xla, s_krn))

    # momentum-SGD: fp32 fused vs the optax chain, bitwise
    sgd_ref = optax_run(optax.chain(optax.add_decayed_weights(wd),
                                    optax.sgd(lr, momentum=0.9)))
    sgd_fused, _ = _run_fused(fused_sgd(lr, 0.9, wd, bucket_mb=0.05),
                              params, grads, steps)
    report["sgdm_fp32_vs_optax_bitwise"] = comm_lib.tree_bitwise_equal(
        sgd_ref, sgd_fused)

    # Adam: fp32 fused vs optax.adamw, float tolerance
    adam_ref = optax_run(optax.adamw(lr, weight_decay=wd))
    adam_fused, _ = _run_fused(fused_adam(lr, weight_decay=wd,
                                          bucket_mb=0.05),
                               params, grads, steps)
    err = max(float(jnp.max(jnp.abs(a - b)))
              for a, b in zip(jax.tree.leaves(adam_ref),
                              jax.tree.leaves(adam_fused)))
    report["adam_fp32_vs_optax_max_err"] = err
    report["adam_fp32_vs_optax_close"] = err <= 1e-5

    # kernel == XLA, every optimizer x quant mode
    quants = ["off", "int8"] + (["fp8"] if ok.fp8_dtype() else [])
    for q in quants:
        report[f"sgdm_{q}_kernel_bitwise"] = kernel_vs_xla(
            fused_sgd(lr, 0.9, wd, quant=q, bucket_mb=0.05))
        report[f"adam_{q}_kernel_bitwise"] = kernel_vs_xla(
            fused_adam(lr, weight_decay=wd, quant=q, bucket_mb=0.05))
    report["ok"] = all(v for k, v in report.items()
                       if k.endswith(("_bitwise", "_close")))
    return report


# -- convergence-parity smoke (the CI gate for quantized moments) ------------


def convergence_smoke(quant: str = "int8", steps: int = 40,
                      envelope: float = 0.25) -> dict:
    """Quantized-moment convergence vs the dense optax reference.

    Same discipline as comm.convergence_smoke: momentum-SGD trains the
    BN CNN, Adam trains the markov transformer, each against its dense
    reference from the SAME init; both runs must LEARN and the
    quantized run must keep >= 1-envelope of dense's loss improvement
    (relative envelope — one pin across models whose loss scales
    differ by 40x).
    """
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train.state import TrainState
    from edl_tpu.train.step import make_train_step

    world = jax.device_count()
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    report: dict = {"quant": quant, "steps": steps,
                    "envelope": envelope, "world": world}

    def run(name, loss_fn, state_dense, state_q, batch):
        placed = mesh_lib.shard_batch(mesh, batch)
        rep = lambda t: jax.device_put(  # noqa: E731
            t, NamedSharding(mesh, P()))
        step = make_train_step(loss_fn, donate=False)
        s_a = jax.tree.map(rep, state_dense)
        s_b = jax.tree.map(rep, state_q)
        first = last_a = last_b = None
        for _ in range(steps):
            s_a, m_a = step(s_a, placed)
            s_b, m_b = step(s_b, placed)
            if first is None:
                first = float(m_a["loss"])
            last_a, last_b = float(m_a["loss"]), float(m_b["loss"])
        delta = abs(last_a - last_b)
        improvement = max(first - last_a, 1e-9)
        report[name] = {
            "loss_initial": round(first, 4),
            "loss_dense": round(last_a, 4),
            "loss_quant": round(last_b, 4),
            "delta_rel": round(delta / improvement, 5),
            "opt_bytes_dense": opt_state_bytes(state_dense.opt_state),
            "opt_bytes_quant": opt_state_bytes(state_q.opt_state),
            "learned": last_a < first and last_b < first,
            "within_envelope": delta <= envelope * improvement}

    # momentum-SGD on the BN CNN (batch_stats ride apply_gradients)
    loss_fn, state, batch = comm_lib._smoke_cnn(world)
    state_q = TrainState.create(
        apply_fn=state.apply_fn, params=state.params,
        tx=fused_sgd(0.05, 0.9, quant=quant, bucket_mb=0.05),
        batch_stats=state.batch_stats)
    run("cnn_sgdm", loss_fn, state, state_q, batch)

    # Adam on the markov transformer
    loss_fn, state, batch = comm_lib._smoke_transformer(world, mesh)
    lr = 1e-2
    state_a = TrainState.create(apply_fn=state.apply_fn,
                                params=state.params,
                                tx=optax.adamw(lr))
    state_q = TrainState.create(
        apply_fn=state.apply_fn, params=state.params,
        tx=fused_adam(lr, quant=quant, bucket_mb=0.05))
    run("transformer_adam", loss_fn, state_a, state_q, batch)

    report["ok"] = all(
        report[k]["learned"] and report[k]["within_envelope"]
        and report[k]["opt_bytes_dense"]
        >= 1.8 * report[k]["opt_bytes_quant"]
        for k in ("cnn_sgdm", "transformer_adam"))
    return report


def _main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(prog="edl_tpu.train.fused_opt")
    sub = parser.add_subparsers(dest="cmd", required=True)
    smoke = sub.add_parser(
        "smoke", help="fused-optimizer gate: interpret-mode kernel "
                      "equivalence + quantized-moment convergence "
                      "parity vs the dense optax reference")
    smoke.add_argument("--quant", choices=("int8", "fp8"),
                       default="int8")
    smoke.add_argument("--steps", type=int, default=40)
    smoke.add_argument("--envelope", type=float, default=0.25,
                       help="RELATIVE loss envelope: the quantized run "
                            "must keep >= 1-envelope of dense's loss "
                            "improvement")
    args = parser.parse_args(argv)
    gate = update_parity_gate()
    conv = convergence_smoke(quant=args.quant, steps=args.steps,
                             envelope=args.envelope)
    report = {"kernel_gate": gate, "convergence": conv,
              "ok": gate["ok"] and conv["ok"]}
    print(json.dumps({"fused_opt_smoke": report}))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(_main())
