"""Dynamic loss scaling — the reference's fp16 mixed-precision knob.

Capability of train_with_fleet.py:68-72,318-321 (`--fp16`,
`--scale_loss`, Paddle's `decorate(..., use_dynamic_loss_scaling=True)`):
scale the loss before the backward so fp16 gradients don't underflow,
unscale before the update, SKIP the step when any gradient is non-finite
(halving the scale), and grow the scale after a run of clean steps.

On TPU the native story is bf16 (same exponent range as fp32 — no
scaling needed), which is why the trainers default to bf16 and the
transform lives off the hot path. It exists for capability parity and
for fp16-activation experiments; it is jit-safe (the skip is a
`tree_map(where(...))`, not Python control flow). Use through
`make_train_step(loss_fn, loss_scale=True)`, whose step signature
becomes `step(state, batch, ls) -> (state, metrics, ls)` with `ls`
built ONCE via `DynamicLossScale.create()` (the bare NamedTuple
constructor leaves scale=None) and threaded through every call
(`lm_train --fp16` shows the TrainLoop closure-cell pattern).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class DynamicLossScale(NamedTuple):
    """Loss-scale state. Defaults match the reference's Paddle decorate
    defaults (init 2^15, 2x growth every 2000 clean steps, 0.5x on
    overflow) within the usual AMP conventions."""

    scale: jnp.ndarray = None  # type: ignore[assignment]
    growth_count: jnp.ndarray = None  # type: ignore[assignment]
    growth_interval: int = 2000

    @staticmethod
    def create(init_scale: float = 2.0 ** 15,
               growth_interval: int = 2000) -> "DynamicLossScale":
        return DynamicLossScale(
            scale=jnp.float32(init_scale),
            growth_count=jnp.int32(0),
            growth_interval=growth_interval)


def all_finite(tree) -> jax.Array:
    leaves = [jnp.all(jnp.isfinite(leaf)) for leaf in jax.tree.leaves(tree)]
    return jnp.stack(leaves).all() if leaves else jnp.bool_(True)


def scaled_value_and_grad(loss_fn, params, ls: DynamicLossScale):
    """value_and_grad of `ls.scale * loss`, with grads unscaled back.

    loss_fn: params -> (loss, aux). Returns ((loss, aux), grads) where
    grads may be non-finite — feed them to `update_scale_and_select`.
    """

    def scaled(p):
        loss, aux = loss_fn(p)
        return loss * ls.scale.astype(loss.dtype), (loss, aux)

    (_, (loss, aux)), grads = jax.value_and_grad(
        scaled, has_aux=True)(params)
    inv = (1.0 / ls.scale).astype(jnp.float32)
    grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
    return (loss, aux), grads


def update_scale_and_select(ls: DynamicLossScale, grads, new_tree,
                            old_tree):
    """One AMP bookkeeping step, jit-safe.

    Returns (new_ls, selected_tree, finite): on non-finite grads the
    scale halves (floor 1.0) and `old_tree` is kept (the skipped step);
    otherwise the growth counter advances, doubling the scale every
    `growth_interval` clean steps (cap 2^24), and `new_tree` is taken.
    """
    finite = all_finite(grads)
    count = jnp.where(finite, ls.growth_count + 1, 0)
    grow = finite & (count >= ls.growth_interval)
    scale = jnp.where(
        finite,
        jnp.where(grow, jnp.minimum(ls.scale * 2.0, 2.0 ** 24), ls.scale),
        jnp.maximum(ls.scale * 0.5, 1.0))
    count = jnp.where(grow, 0, count)
    selected = jax.tree.map(
        lambda new, old: jnp.where(finite, new, old), new_tree, old_tree)
    return (DynamicLossScale(scale=scale, growth_count=count,
                             growth_interval=ls.growth_interval),
            selected, finite)
