"""Per-shard checkpoint serialization with resharding restore.

The sharded half of the checkpoint story (capability target: the
reference's fleet checkpoints, doc/fault_tolerance.md:1-67, scaled to
states that never fit one host): at save, every process writes only the
array shards it owns (deduplicated by replica id) plus a chunk index; at
restore, each device's shard is assembled from whichever saved chunks
intersect it — saved-mesh and restore-mesh shapes are independent, so an
fsdp x tp state saved on 8 devices re-places onto 4 (or 32) by the
target's sharding rules. Chunk reads go through numpy memory-maps, so
restore materializes per-target-shard regions, never the full array.

Layout inside a checkpoint directory:
  leaf{i}-o{start}_{start}...npy   one file per unique array chunk
  index.{process}.json             that process's chunk table + leaf specs

The format is self-describing; `is_sharded_dir` lets a manager
auto-detect it next to the replicated msgpack format.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any

import jax
import numpy as np

from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.sharded_checkpoint")

_INDEX_RE = re.compile(r"^index\.(\d+)\.json$")


def _leaf_key(path) -> str:
    return jax.tree_util.keystr(path)


def _chunk_name(leaf_i: int, offset: tuple[int, ...]) -> str:
    tag = "_".join(str(o) for o in offset) if offset else "scalar"
    return f"leaf{leaf_i}-o{tag}.npy"


def _slices_to_offset_shape(index: tuple, shape: tuple[int, ...]
                            ) -> tuple[tuple[int, ...], tuple[int, ...]]:
    offset, size = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offset.append(start)
        size.append(stop - start)
    return tuple(offset), tuple(size)


def save_sharded(directory: str, state: Any) -> list[str]:
    """Write this process's unique shards of `state` into `directory`.

    Every process of the world must call this with the same state; chunks
    are deduplicated so each array region is written exactly once
    world-wide (the writer is the shard with replica_id == 0). Returns
    the basenames of the files THIS process wrote (its chunks + its index
    file) — what a non-shared-FS mirror must upload from this host.
    """
    os.makedirs(directory, exist_ok=True)
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    written: list[str] = []
    table = []
    for i, (path, leaf) in enumerate(leaves):
        key = _leaf_key(path)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shape = tuple(leaf.shape)
            dtype = str(leaf.dtype)
            chunks = []
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                offset, size = _slices_to_offset_shape(shard.index, shape)
                fname = _chunk_name(i, offset)
                np.save(os.path.join(directory, fname),
                        np.asarray(shard.data))
                written.append(fname)
                chunks.append({"offset": list(offset), "shape": list(size),
                               "file": fname})
        else:  # host scalar / numpy leaf — process 0 owns it whole
            arr = np.asarray(leaf)
            shape, dtype = tuple(arr.shape), str(arr.dtype)
            chunks = []
            if jax.process_index() == 0:
                offset = tuple(0 for _ in shape)
                fname = _chunk_name(i, offset)
                np.save(os.path.join(directory, fname), arr)
                written.append(fname)
                chunks.append({"offset": list(offset),
                               "shape": list(arr.shape), "file": fname})
        table.append({"key": key, "shape": list(shape), "dtype": dtype,
                      "chunks": chunks})
    index_name = f"index.{jax.process_index()}.json"
    with open(os.path.join(directory, index_name), "w") as f:
        json.dump({"leaves": table}, f)
    written.append(index_name)
    return written


def _merged_index(directory: str) -> dict[str, dict]:
    """key -> {shape, dtype, chunks[]} merged across all process indexes."""
    merged: dict[str, dict] = {}
    paths = glob.glob(os.path.join(directory, "index.*.json"))
    if not paths:
        raise FileNotFoundError(f"no index.*.json under {directory}")
    for p in sorted(paths):
        with open(p) as f:
            data = json.load(f)
        for leaf in data["leaves"]:
            entry = merged.setdefault(
                leaf["key"], {"shape": leaf["shape"], "dtype": leaf["dtype"],
                              "chunks": []})
            if entry["shape"] != leaf["shape"]:
                raise ValueError(
                    f"shape mismatch across index files for {leaf['key']}")
            entry["chunks"].extend(leaf["chunks"])
    return merged


def _read_region(directory: str, entry: dict, index: tuple) -> np.ndarray:
    """Assemble the region `index` (tuple of slices) from saved chunks."""
    shape = tuple(entry["shape"])
    offset, size = _slices_to_offset_shape(index, shape)
    out = np.empty(size, dtype=np.dtype(entry["dtype"]))
    # Coverage mask (not an element count): overlapping chunks — e.g. a
    # half-written dir mixing two world shapes — must not mask a hole.
    covered = np.zeros(size, dtype=bool)
    for chunk in entry["chunks"]:
        coff, cshape = chunk["offset"], chunk["shape"]
        lo = [max(o, co) for o, co in zip(offset, coff)]
        hi = [min(o + s, co + cs)
              for o, s, co, cs in zip(offset, size, coff, cshape)]
        if any(a >= b for a, b in zip(lo, hi)):
            continue
        src = np.load(os.path.join(directory, chunk["file"]), mmap_mode="r")
        src_sel = tuple(slice(a - co, b - co)
                        for a, b, co in zip(lo, hi, coff))
        dst_sel = tuple(slice(a - o, b - o)
                        for a, b, o in zip(lo, hi, offset))
        out[dst_sel] = src[src_sel]
        covered[dst_sel] = True
    if not covered.all():
        missing = int(covered.size - np.count_nonzero(covered))
        raise ValueError(
            f"chunks leave {missing}/{covered.size} elements of region "
            f"{offset}+{size} unwritten — checkpoint incomplete for this "
            f"resharding")
    return out


def restore_sharded(directory: str, target: Any) -> Any:
    """Re-place a sharded checkpoint onto `target`'s shardings.

    `target` is a pytree whose array leaves carry the DESTINATION sharding
    (materialized arrays on the new mesh, or jax.ShapeDtypeStruct with a
    `sharding` set) — typically the freshly initialized state of the new
    world. Leaves are assembled chunk-wise per target shard, so a state
    saved on one mesh shape restores onto any other.
    """
    merged = _merged_index(directory)
    leaves, treedef = jax.tree_util.tree_flatten_with_path(target)
    out = []
    for path, leaf in leaves:
        key = _leaf_key(path)
        entry = merged.get(key)
        if entry is None:
            raise KeyError(f"checkpoint has no leaf {key}")
        shape = tuple(entry["shape"])
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, jax.sharding.NamedSharding):
            # Leaf without a mesh placement (eagerly created scalars like
            # opt-state counters, or host leaves): restore as host numpy —
            # uncommitted, so a following jit places it freely instead of
            # pinning it to one device of somebody else's mesh.
            sharding = None
        if isinstance(leaf, jax.Array) and sharding is not None:
            if tuple(leaf.shape) != shape:
                raise ValueError(
                    f"{key}: target shape {tuple(leaf.shape)} != saved "
                    f"{shape}")
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, e=entry: _read_region(directory, e, idx))
            # preserve weak_type of scalars created by jit (e.g. step)
            out.append(arr.astype(leaf.dtype) if arr.dtype != leaf.dtype
                       else arr)
        else:
            full = _read_region(directory, entry,
                                tuple(slice(0, s) for s in shape))
            out.append(full if shape else full[()])
    return jax.tree_util.tree_unflatten(treedef, out)


def is_sharded_dir(directory: str) -> bool:
    try:
        return any(_INDEX_RE.match(n) for n in os.listdir(directory))
    except FileNotFoundError:
        return False
