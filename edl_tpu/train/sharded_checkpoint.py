"""Per-shard checkpoint serialization with resharding restore.

The sharded half of the checkpoint story (capability target: the
reference's fleet checkpoints, doc/fault_tolerance.md:1-67, scaled to
states that never fit one host): at save, every process writes only the
array shards it owns (deduplicated by replica id) plus a chunk index; at
restore, each device's shard is assembled from whichever saved chunks
intersect it — saved-mesh and restore-mesh shapes are independent, so an
fsdp x tp state saved on 8 devices re-places onto 4 (or 32) by the
target's sharding rules. Chunk reads go through numpy memory-maps, so
restore materializes per-target-shard regions, never the full array.
The planner is sharding-GENERIC: MoE expert tables (leading "expert"
logical axis -> ep, sharding.DEFAULT_RULES) are ordinary sharded
leaves here, so an ep resize (4 -> 2 experts-per-chip doubling, or
back) reshards expert tables through this same path — from disk or,
via ``restore_from_index`` with a peer-fetch loader, from donor
memory (collective/migration.py) with zero process restarts.

Save splits into two halves so the async checkpoint plane
(train/checkpoint.py `save_async`) can run them on different threads:
``snapshot_shards`` pulls this process's unique chunks to host (the only
part that must block the step loop), ``write_snapshot`` does the disk
I/O. ``save_sharded`` composes them, so sync and async saves produce
bitwise-identical files.

Restore reads regions through a per-file handle cache (each chunk is
np.load'ed once per restore, not once per intersecting region) and, when
``threads > 1``, prefetches every region on a thread pool before
assembly — elastic re-formation wants the restore off the downtime
budget as much as the save off the step loop.

Integrity: ``write_snapshot`` records a crc32 per chunk in the index;
restore verifies each chunk file once on first load (disk) — and the
migration plane verifies peer-fetched chunks against the donor
manifest's same numbers — raising the typed ``EdlCheckpointCorrupt``
so callers fall back (previous sealed version / another donor) instead
of loading garbage. ``EDL_TPU_CKPT_VERIFY=0`` disables.

The numpy-only file halves (chunk naming, crc, write, merge, region
assembly) live in ``train/ckpt_io.py`` so jax-free consumers — the
chaos plane's corruptor and soak workers — speak the same format; this
module re-exports them for compatibility and keeps the jax halves.

Layout inside a checkpoint directory:
  leaf{i}-o{start}_{start}...npy   one file per unique array chunk
  index.{process}.json             that process's chunk table + leaf specs

The format is self-describing; `is_sharded_dir` lets a manager
auto-detect it next to the replicated msgpack format.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import jax
import numpy as np

from edl_tpu.train import ckpt_io
from edl_tpu.train.ckpt_io import (  # noqa: F401 — compat re-exports
    ChunkFiles as _ChunkFiles,
    checksum_map,
    chunk_crc32,
    is_sharded_dir,
    merge_leaf_tables,
    read_region as _read_region,
    verify_enabled,
    write_snapshot,
)
from edl_tpu.utils import config
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.sharded_checkpoint")

_INDEX_RE = ckpt_io._INDEX_RE
_chunk_name = ckpt_io.chunk_name
_slices_to_offset_shape = ckpt_io.slices_to_offset_shape
_merged_index = ckpt_io.read_merged_index


def _leaf_key(path) -> str:
    return jax.tree_util.keystr(path)


def snapshot_shards(state: Any) -> dict:
    """Host snapshot of this process's unique shards of ``state``.

    The device->host pull half of ``save_sharded`` — the only part that
    must run on the training thread (and the only part whose duration
    the step loop pays under async saves). Returns ``{"leaves": table,
    "chunks": [(fname, array), ...]}`` where the arrays MAY alias device
    buffers on the CPU backend (np.asarray of an aligned shard is
    zero-copy) — a caller that defers the write past the next train step
    must copy them first (checkpoint.py stages them into its snapshot
    arena).
    """
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    chunks_out: list[tuple[str, np.ndarray]] = []
    table = []
    for i, (path, leaf) in enumerate(leaves):
        key = _leaf_key(path)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shape = tuple(leaf.shape)
            dtype = str(leaf.dtype)
            chunks = []
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                offset, size = _slices_to_offset_shape(shard.index, shape)
                fname = _chunk_name(i, offset)
                chunks_out.append((fname, np.asarray(shard.data)))
                chunks.append({"offset": list(offset), "shape": list(size),
                               "file": fname})
        else:  # host scalar / numpy leaf — process 0 owns it whole
            arr = np.asarray(leaf)
            shape, dtype = tuple(arr.shape), str(arr.dtype)
            chunks = []
            if jax.process_index() == 0:
                offset = tuple(0 for _ in shape)
                fname = _chunk_name(i, offset)
                chunks_out.append((fname, arr))
                chunks.append({"offset": list(offset),
                               "shape": list(arr.shape), "file": fname})
        table.append({"key": key, "shape": list(shape), "dtype": dtype,
                      "chunks": chunks})
    return {"leaves": table, "chunks": chunks_out,
            "process_index": jax.process_index()}


def save_sharded(directory: str, state: Any) -> list[str]:
    """Write this process's unique shards of `state` into `directory`.

    Every process of the world must call this with the same state; chunks
    are deduplicated so each array region is written exactly once
    world-wide (the writer is the shard with replica_id == 0). Returns
    the basenames of the files THIS process wrote (its chunks + its index
    file) — what a non-shared-FS mirror must upload from this host.
    """
    return write_snapshot(directory, snapshot_shards(state))


def snapshot_nbytes(snap: dict) -> int:
    """Total payload bytes of a snapshot's chunks — what a donor advert
    quotes and a full peer restore moves over the wire.

    Accepts both chunk layouts: the ``snapshot_shards`` /
    ``snapshot_host_tree`` list of ``(fname, array)`` pairs and the
    ``sealed_snapshot`` fname->array dict. Counts bytes AS STORED, so
    quantized optimizer moments (train/fused_opt.py's int8 ``(q, scale,
    rq, rscale)`` planes — ordinary pytree leaves to this format) show
    their ~2x cut on disk and on the migration wire, not only in HBM:
    the codes are serialized and shipped, never a dequantized fp32
    copy."""
    chunks = snap["chunks"]
    arrays = chunks.values() if isinstance(chunks, dict) else (
        a for _, a in chunks)
    return int(sum(a.nbytes for a in arrays))


def snapshot_host_tree(state: Any) -> dict:
    """Leaf-table + full-array-chunk view of a HOST pytree.

    The replicated checkpoint payload (rank 0's `jax.device_get` tree)
    expressed in the same self-describing structure `snapshot_shards`
    emits: every leaf is one chunk covering the whole array, owned by
    process 0. This is what lets the state-migration plane serve
    replicated AND sharded snapshots through one region planner —
    a peer restoring from a replicated donor plans regions against this
    table exactly as it would against on-disk chunk indexes. Chunk
    crc32s are recorded here (not only at write time) so a replicated
    donor's manifest carries checksums for the peer-fetch verify."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    chunks_out: list[tuple[str, np.ndarray]] = []
    table = []
    for i, (path, leaf) in enumerate(leaves):
        arr = np.asarray(leaf)
        offset = tuple(0 for _ in arr.shape)
        fname = _chunk_name(i, offset)
        chunks_out.append((fname, arr))
        table.append({"key": _leaf_key(path), "shape": list(arr.shape),
                      "dtype": str(arr.dtype),
                      "chunks": [{"offset": list(offset),
                                  "shape": list(arr.shape),
                                  "file": fname,
                                  "crc32": chunk_crc32(arr)}]})
    return {"leaves": table, "chunks": chunks_out, "process_index": 0}


def restore_threads() -> int:
    """Region-read pool width for restore (the restore-side half of the
    elastic downtime budget). Env-tunable; defaults past 1 even on small
    hosts because the reads are mmap-page-in bound, not CPU bound."""
    configured = config.env_int("EDL_TPU_CKPT_RESTORE_THREADS", 0)
    if configured > 0:
        return configured
    return min(8, 2 * (os.cpu_count() or 1))


def _region_key(index: tuple, shape: tuple[int, ...]) -> tuple:
    return _slices_to_offset_shape(index, shape)


def restore_sharded(directory: str, target: Any,
                    threads: int | None = None) -> Any:
    """Re-place a sharded checkpoint onto `target`'s shardings.

    `target` is a pytree whose array leaves carry the DESTINATION sharding
    (materialized arrays on the new mesh, or jax.ShapeDtypeStruct with a
    `sharding` set) — typically the freshly initialized state of the new
    world. Leaves are assembled chunk-wise per target shard, so a state
    saved on one mesh shape restores onto any other.

    ``threads``: region-read pool width (default `restore_threads()`,
    env ``EDL_TPU_CKPT_RESTORE_THREADS``); every unique target region is
    prefetched concurrently before device placement, and 1 keeps the
    serial path. Chunk integrity is verified against the index's sealed
    crc32s (``EDL_TPU_CKPT_VERIFY``); corruption raises
    ``EdlCheckpointCorrupt`` — CheckpointManager.restore falls back to
    the previous sealed version on it.
    """
    merged = _merged_index(directory)
    files = _ChunkFiles(directory, crcs=checksum_map(merged))
    try:
        return restore_from_index(merged, files.load, target, threads)
    finally:
        files.close()


def restore_from_index(merged: dict[str, dict], load, target: Any,
                       threads: int | None = None) -> Any:
    """The resharding planner behind `restore_sharded`, with the chunk
    source abstracted: plan every unique (leaf, region) the TARGET's
    shardings need, read regions through ``load(fname) -> ndarray``
    (thread-pooled), assemble via `jax.make_array_from_callback`. The
    state-migration plane drives this with a peer-fetch loader so the
    SAME planner that reshards on-disk checkpoints reshards donor
    memory across the wire.
    """
    if threads is None:
        threads = restore_threads()
    leaves, treedef = jax.tree_util.tree_flatten_with_path(target)

    # Plan every unique region to read: one entry per (leaf, region) —
    # a dp-replicated target asks for the same region once per replica,
    # the cache below reads it once.
    plans = []   # (key, entry, sharding|None, leaf, [region indexes])
    for path, leaf in leaves:
        key = _leaf_key(path)
        entry = merged.get(key)
        if entry is None:
            raise KeyError(f"checkpoint has no leaf {key}")
        shape = tuple(entry["shape"])
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, jax.sharding.NamedSharding):
            # Leaf without a mesh placement (eagerly created scalars like
            # opt-state counters, or host leaves): restore as host numpy —
            # uncommitted, so a following jit places it freely instead of
            # pinning it to one device of somebody else's mesh.
            sharding = None
        if isinstance(leaf, jax.Array) and sharding is not None:
            if tuple(leaf.shape) != shape:
                raise ValueError(
                    f"{key}: target shape {tuple(leaf.shape)} != saved "
                    f"{shape}")
            try:
                idx_map = sharding.addressable_devices_indices_map(shape)
            except AttributeError:  # older jax: no prefetch plan — the
                idx_map = {}        # callback reads on demand (cached)
            uniq = {_region_key(idx, shape): idx for idx in idx_map.values()}
            plans.append((key, entry, sharding, leaf, list(uniq.values())))
        else:
            plans.append((key, entry, None, leaf,
                          [tuple(slice(0, s) for s in shape)]))

    regions: dict[tuple, np.ndarray] = {}

    def read(entry, idx):
        k = (id(entry), _region_key(idx, tuple(entry["shape"])))
        regions[k] = _read_region(load, entry, idx)

    jobs = [(entry, idx) for _, entry, _, _, idxs in plans for idx in idxs]
    if threads > 1 and len(jobs) > 1:
        with ThreadPoolExecutor(max_workers=threads,
                                thread_name_prefix="edl-ckpt-read") as pool:
            # list() re-raises the first read error (coverage holes must
            # fail the restore loudly, threaded or not)
            list(pool.map(lambda j: read(*j), jobs))
    else:
        for j in jobs:
            read(*j)

    out = []
    for key, entry, sharding, leaf, idxs in plans:
        shape = tuple(entry["shape"])
        if sharding is not None:
            def region(idx, e=entry):
                k = (id(e), _region_key(idx, tuple(e["shape"])))
                if k not in regions:  # older-jax fallback: no prefetch plan
                    regions[k] = _read_region(load, e, idx)
                return regions[k]

            arr = jax.make_array_from_callback(shape, sharding, region)
            # preserve weak_type of scalars created by jit (e.g. step)
            out.append(arr.astype(leaf.dtype) if arr.dtype != leaf.dtype
                       else arr)
        else:
            full = regions[(id(entry), _region_key(idxs[0], shape))]
            out.append(full if shape else full[()])
    return jax.tree_util.tree_unflatten(treedef, out)
