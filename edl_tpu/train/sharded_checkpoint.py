"""Per-shard checkpoint serialization with resharding restore.

The sharded half of the checkpoint story (capability target: the
reference's fleet checkpoints, doc/fault_tolerance.md:1-67, scaled to
states that never fit one host): at save, every process writes only the
array shards it owns (deduplicated by replica id) plus a chunk index; at
restore, each device's shard is assembled from whichever saved chunks
intersect it — saved-mesh and restore-mesh shapes are independent, so an
fsdp x tp state saved on 8 devices re-places onto 4 (or 32) by the
target's sharding rules. Chunk reads go through numpy memory-maps, so
restore materializes per-target-shard regions, never the full array.

Save splits into two halves so the async checkpoint plane
(train/checkpoint.py `save_async`) can run them on different threads:
``snapshot_shards`` pulls this process's unique chunks to host (the only
part that must block the step loop), ``write_snapshot`` does the disk
I/O. ``save_sharded`` composes them, so sync and async saves produce
bitwise-identical files.

Restore reads regions through a per-file handle cache (each chunk is
np.load'ed once per restore, not once per intersecting region) and, when
``threads > 1``, prefetches every region on a thread pool before
assembly — elastic re-formation wants the restore off the downtime
budget as much as the save off the step loop.

Layout inside a checkpoint directory:
  leaf{i}-o{start}_{start}...npy   one file per unique array chunk
  index.{process}.json             that process's chunk table + leaf specs

The format is self-describing; `is_sharded_dir` lets a manager
auto-detect it next to the replicated msgpack format.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import jax
import numpy as np

from edl_tpu.utils import config
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.sharded_checkpoint")

_INDEX_RE = re.compile(r"^index\.(\d+)\.json$")


def _leaf_key(path) -> str:
    return jax.tree_util.keystr(path)


def _chunk_name(leaf_i: int, offset: tuple[int, ...]) -> str:
    tag = "_".join(str(o) for o in offset) if offset else "scalar"
    return f"leaf{leaf_i}-o{tag}.npy"


def _slices_to_offset_shape(index: tuple, shape: tuple[int, ...]
                            ) -> tuple[tuple[int, ...], tuple[int, ...]]:
    offset, size = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offset.append(start)
        size.append(stop - start)
    return tuple(offset), tuple(size)


def snapshot_shards(state: Any) -> dict:
    """Host snapshot of this process's unique shards of ``state``.

    The device->host pull half of ``save_sharded`` — the only part that
    must run on the training thread (and the only part whose duration
    the step loop pays under async saves). Returns ``{"leaves": table,
    "chunks": [(fname, array), ...]}`` where the arrays MAY alias device
    buffers on the CPU backend (np.asarray of an aligned shard is
    zero-copy) — a caller that defers the write past the next train step
    must copy them first (checkpoint.py stages them into its snapshot
    arena).
    """
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    chunks_out: list[tuple[str, np.ndarray]] = []
    table = []
    for i, (path, leaf) in enumerate(leaves):
        key = _leaf_key(path)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            shape = tuple(leaf.shape)
            dtype = str(leaf.dtype)
            chunks = []
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                offset, size = _slices_to_offset_shape(shard.index, shape)
                fname = _chunk_name(i, offset)
                chunks_out.append((fname, np.asarray(shard.data)))
                chunks.append({"offset": list(offset), "shape": list(size),
                               "file": fname})
        else:  # host scalar / numpy leaf — process 0 owns it whole
            arr = np.asarray(leaf)
            shape, dtype = tuple(arr.shape), str(arr.dtype)
            chunks = []
            if jax.process_index() == 0:
                offset = tuple(0 for _ in shape)
                fname = _chunk_name(i, offset)
                chunks_out.append((fname, arr))
                chunks.append({"offset": list(offset),
                               "shape": list(arr.shape), "file": fname})
        table.append({"key": key, "shape": list(shape), "dtype": dtype,
                      "chunks": chunks})
    return {"leaves": table, "chunks": chunks_out,
            "process_index": jax.process_index()}


def write_snapshot(directory: str, snap: dict) -> list[str]:
    """Write a ``snapshot_shards`` result into ``directory``.

    The disk half of ``save_sharded`` — safe to run on a background
    thread (pure numpy + file I/O, no device access). Returns the
    basenames this process wrote (chunks + its index file), index last
    so its presence implies the chunks made it.
    """
    os.makedirs(directory, exist_ok=True)
    written: list[str] = []
    for fname, arr in snap["chunks"]:
        np.save(os.path.join(directory, fname), arr)
        written.append(fname)
    index_name = f"index.{snap['process_index']}.json"
    with open(os.path.join(directory, index_name), "w") as f:
        json.dump({"leaves": snap["leaves"]}, f)
    written.append(index_name)
    return written


def save_sharded(directory: str, state: Any) -> list[str]:
    """Write this process's unique shards of `state` into `directory`.

    Every process of the world must call this with the same state; chunks
    are deduplicated so each array region is written exactly once
    world-wide (the writer is the shard with replica_id == 0). Returns
    the basenames of the files THIS process wrote (its chunks + its index
    file) — what a non-shared-FS mirror must upload from this host.
    """
    return write_snapshot(directory, snapshot_shards(state))


def snapshot_host_tree(state: Any) -> dict:
    """Leaf-table + full-array-chunk view of a HOST pytree.

    The replicated checkpoint payload (rank 0's `jax.device_get` tree)
    expressed in the same self-describing structure `snapshot_shards`
    emits: every leaf is one chunk covering the whole array, owned by
    process 0. This is what lets the state-migration plane serve
    replicated AND sharded snapshots through one region planner —
    a peer restoring from a replicated donor plans regions against this
    table exactly as it would against on-disk chunk indexes.
    """
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    chunks_out: list[tuple[str, np.ndarray]] = []
    table = []
    for i, (path, leaf) in enumerate(leaves):
        arr = np.asarray(leaf)
        offset = tuple(0 for _ in arr.shape)
        fname = _chunk_name(i, offset)
        chunks_out.append((fname, arr))
        table.append({"key": _leaf_key(path), "shape": list(arr.shape),
                      "dtype": str(arr.dtype),
                      "chunks": [{"offset": list(offset),
                                  "shape": list(arr.shape),
                                  "file": fname}]})
    return {"leaves": table, "chunks": chunks_out, "process_index": 0}


def merge_leaf_tables(tables: list[list[dict]]) -> dict[str, dict]:
    """key -> {shape, dtype, chunks[]} merged across per-process leaf
    tables (the `leaves` list of an index file, a `snapshot_shards`
    result, or a migration donor's manifest)."""
    merged: dict[str, dict] = {}
    for leaves in tables:
        for leaf in leaves:
            entry = merged.setdefault(
                leaf["key"], {"shape": leaf["shape"], "dtype": leaf["dtype"],
                              "chunks": []})
            if entry["shape"] != leaf["shape"]:
                raise ValueError(
                    f"shape mismatch across leaf tables for {leaf['key']}")
            entry["chunks"].extend(leaf["chunks"])
    return merged


def _merged_index(directory: str) -> dict[str, dict]:
    """key -> {shape, dtype, chunks[]} merged across all process indexes."""
    paths = glob.glob(os.path.join(directory, "index.*.json"))
    if not paths:
        raise FileNotFoundError(f"no index.*.json under {directory}")
    tables = []
    for p in sorted(paths):
        with open(p) as f:
            tables.append(json.load(f)["leaves"])
    return merge_leaf_tables(tables)


class _ChunkFiles:
    """Per-restore cache of memory-mapped chunk files.

    A resharding restore reads the same chunk for every target region it
    intersects; re-running np.load per region paid a file open + header
    parse each time. One handle per file, shared across regions (and
    across reader threads — numpy memmap reads are thread-safe)."""

    def __init__(self, directory: str):
        self.directory = directory
        self._handles: dict[str, np.ndarray] = {}
        self._lock = threading.Lock()

    def load(self, fname: str) -> np.ndarray:
        with self._lock:
            h = self._handles.get(fname)
            if h is None:
                h = np.load(os.path.join(self.directory, fname),
                            mmap_mode="r")
                self._handles[fname] = h
            return h

    def close(self) -> None:
        self._handles.clear()  # memmaps close when the views are collected


def _read_region(load, entry: dict, index: tuple) -> np.ndarray:
    """Assemble the region `index` (tuple of slices) from saved chunks.

    ``load(fname) -> ndarray`` is the chunk source — a `_ChunkFiles`
    mmap cache for on-disk checkpoints, or a peer-fetch cache when the
    chunks live in a migration donor's memory."""
    shape = tuple(entry["shape"])
    offset, size = _slices_to_offset_shape(index, shape)
    out = np.empty(size, dtype=np.dtype(entry["dtype"]))
    # Coverage mask (not an element count): overlapping chunks — e.g. a
    # half-written dir mixing two world shapes — must not mask a hole.
    covered = np.zeros(size, dtype=bool)
    for chunk in entry["chunks"]:
        coff, cshape = chunk["offset"], chunk["shape"]
        lo = [max(o, co) for o, co in zip(offset, coff)]
        hi = [min(o + s, co + cs)
              for o, s, co, cs in zip(offset, size, coff, cshape)]
        if any(a >= b for a, b in zip(lo, hi)):
            continue
        src = load(chunk["file"])
        src_sel = tuple(slice(a - co, b - co)
                        for a, b, co in zip(lo, hi, coff))
        dst_sel = tuple(slice(a - o, b - o)
                        for a, b, o in zip(lo, hi, offset))
        out[dst_sel] = src[src_sel]
        covered[dst_sel] = True
    if not covered.all():
        missing = int(covered.size - np.count_nonzero(covered))
        raise ValueError(
            f"chunks leave {missing}/{covered.size} elements of region "
            f"{offset}+{size} unwritten — checkpoint incomplete for this "
            f"resharding")
    return out


def restore_threads() -> int:
    """Region-read pool width for restore (the restore-side half of the
    elastic downtime budget). Env-tunable; defaults past 1 even on small
    hosts because the reads are mmap-page-in bound, not CPU bound."""
    configured = config.env_int("EDL_TPU_CKPT_RESTORE_THREADS", 0)
    if configured > 0:
        return configured
    return min(8, 2 * (os.cpu_count() or 1))


def _region_key(index: tuple, shape: tuple[int, ...]) -> tuple:
    return _slices_to_offset_shape(index, shape)


def restore_sharded(directory: str, target: Any,
                    threads: int | None = None) -> Any:
    """Re-place a sharded checkpoint onto `target`'s shardings.

    `target` is a pytree whose array leaves carry the DESTINATION sharding
    (materialized arrays on the new mesh, or jax.ShapeDtypeStruct with a
    `sharding` set) — typically the freshly initialized state of the new
    world. Leaves are assembled chunk-wise per target shard, so a state
    saved on one mesh shape restores onto any other.

    ``threads``: region-read pool width (default `restore_threads()`,
    env ``EDL_TPU_CKPT_RESTORE_THREADS``); every unique target region is
    prefetched concurrently before device placement, and 1 keeps the
    serial path.
    """
    files = _ChunkFiles(directory)
    try:
        return restore_from_index(_merged_index(directory), files.load,
                                  target, threads)
    finally:
        files.close()


def restore_from_index(merged: dict[str, dict], load, target: Any,
                       threads: int | None = None) -> Any:
    """The resharding planner behind `restore_sharded`, with the chunk
    source abstracted: plan every unique (leaf, region) the TARGET's
    shardings need, read regions through ``load(fname) -> ndarray``
    (thread-pooled), assemble via `jax.make_array_from_callback`. The
    state-migration plane drives this with a peer-fetch loader so the
    SAME planner that reshards on-disk checkpoints reshards donor
    memory across the wire.
    """
    if threads is None:
        threads = restore_threads()
    leaves, treedef = jax.tree_util.tree_flatten_with_path(target)

    # Plan every unique region to read: one entry per (leaf, region) —
    # a dp-replicated target asks for the same region once per replica,
    # the cache below reads it once.
    plans = []   # (key, entry, sharding|None, leaf, [region indexes])
    for path, leaf in leaves:
        key = _leaf_key(path)
        entry = merged.get(key)
        if entry is None:
            raise KeyError(f"checkpoint has no leaf {key}")
        shape = tuple(entry["shape"])
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, jax.sharding.NamedSharding):
            # Leaf without a mesh placement (eagerly created scalars like
            # opt-state counters, or host leaves): restore as host numpy —
            # uncommitted, so a following jit places it freely instead of
            # pinning it to one device of somebody else's mesh.
            sharding = None
        if isinstance(leaf, jax.Array) and sharding is not None:
            if tuple(leaf.shape) != shape:
                raise ValueError(
                    f"{key}: target shape {tuple(leaf.shape)} != saved "
                    f"{shape}")
            try:
                idx_map = sharding.addressable_devices_indices_map(shape)
            except AttributeError:  # older jax: no prefetch plan — the
                idx_map = {}        # callback reads on demand (cached)
            uniq = {_region_key(idx, shape): idx for idx in idx_map.values()}
            plans.append((key, entry, sharding, leaf, list(uniq.values())))
        else:
            plans.append((key, entry, None, leaf,
                          [tuple(slice(0, s) for s in shape)]))

    regions: dict[tuple, np.ndarray] = {}

    def read(entry, idx):
        k = (id(entry), _region_key(idx, tuple(entry["shape"])))
        regions[k] = _read_region(load, entry, idx)

    jobs = [(entry, idx) for _, entry, _, _, idxs in plans for idx in idxs]
    if threads > 1 and len(jobs) > 1:
        with ThreadPoolExecutor(max_workers=threads,
                                thread_name_prefix="edl-ckpt-read") as pool:
            # list() re-raises the first read error (coverage holes must
            # fail the restore loudly, threaded or not)
            list(pool.map(lambda j: read(*j), jobs))
    else:
        for j in jobs:
            read(*j)

    out = []
    for key, entry, sharding, leaf, idxs in plans:
        shape = tuple(entry["shape"])
        if sharding is not None:
            def region(idx, e=entry):
                k = (id(e), _region_key(idx, tuple(e["shape"])))
                if k not in regions:  # older-jax fallback: no prefetch plan
                    regions[k] = _read_region(load, e, idx)
                return regions[k]

            arr = jax.make_array_from_callback(shape, sharding, region)
            # preserve weak_type of scalars created by jit (e.g. step)
            out.append(arr.astype(leaf.dtype) if arr.dtype != leaf.dtype
                       else arr)
        else:
            full = regions[(id(entry), _region_key(idxs[0], shape))]
            out.append(full if shape else full[()])
    return jax.tree_util.tree_unflatten(treedef, out)


def is_sharded_dir(directory: str) -> bool:
    try:
        return any(_INDEX_RE.match(n) for n in os.listdir(directory))
    except FileNotFoundError:
        return False
