"""Deep Gradient Compression: optax transform + sparse collective.

Capability of the reference's DGCMomentum flag
(example/collective/resnet50/train_with_fleet.py:98-112: top-k gradient
sparsification with momentum correction and a ramp-up step before
compression kicks in — Lin et al., "Deep Gradient Compression"), split
into its two separable halves, because in a single jitted SPMD program
the optax chain runs AFTER XLA's gradient reduction:

- `dgc(...)`: an `optax.GradientTransformation` with DGC's *update*
  semantics — top-k sparsified steps, momentum correction, dense local
  residual so no gradient mass is ever lost. Chained before the
  optimizer it governs what the parameters see; it does NOT reduce
  communication (the psum already happened upstream). DGC's momentum
  correction replaces optimizer momentum — chain it into a momentum-
  free optimizer:

      tx = optax.chain(dgc(sparsity=0.99, momentum=0.9,
                           rampup_steps=5008),
                       optax.sgd(lr))           # no momentum here

- `sparse_psum(...)`: the *communication* half, for manual-collective
  steps (inside `shard_map`, where the author controls the reduction):
  each worker contributes only its top-k (values, indices), workers
  `all_gather` the compressed pairs — k*(4+4) bytes instead of n*4 over
  DCN — and scatter-add locally. This is the reference's NCCL-bytes
  saving, expressed with XLA collectives and static shapes.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax


_SAMPLE_CAP = 16384


def _topk_threshold(flat: jnp.ndarray, keep_frac: float,
                    step: jnp.ndarray) -> jnp.ndarray:
    """|value| threshold keeping ~keep_frac of entries.

    Exact k-th-largest for small leaves; for big leaves the threshold is
    estimated from a RANDOM sample (the DGC paper's recipe) — a full
    per-leaf per-step top_k is a sort over millions of entries on the
    hot path. The sample is uniform (a strided sample would alias with
    the tensor's inner dimensions — e.g. pick a handful of columns of a
    (R, C) kernel — and bias the threshold by orders of magnitude under
    per-channel scale structure) and the STEP is folded into the key so
    the sampled positions rotate every step: with a frozen sample,
    entries outside it never influence the estimate, a persistent bias
    the paper's per-step resampling avoids."""
    n = flat.size
    if n <= _SAMPLE_CAP:
        k = max(1, int(round(n * keep_frac)))
        return jax.lax.top_k(jnp.abs(flat), k)[0][-1]
    key = jax.random.fold_in(jax.random.PRNGKey(n % (2**31 - 1)), step)
    idx = jax.random.randint(key, (_SAMPLE_CAP,), 0, n)
    sample = jnp.abs(flat[idx])
    k = max(1, int(round(sample.size * keep_frac)))
    return jax.lax.top_k(sample, k)[0][-1]


class DGCState(NamedTuple):
    step: jnp.ndarray        # int32 global step
    momentum: dict           # per-leaf momentum-corrected accumulator
    residual: dict           # per-leaf unsent (masked-out) gradient


def dgc(sparsity: float = 0.99, momentum: float = 0.9,
        rampup_steps: int = 0) -> optax.GradientTransformation:
    """Top-(1-sparsity) gradient sparsification with momentum correction.

    Args:
      sparsity: fraction of each leaf's entries dropped (0.99 sends 1%).
        Small leaves (< 64 entries, e.g. biases/scales) are never
        compressed — matching the reference's behavior of leaving tiny
        params dense.
      momentum: DGC's local momentum factor for the correction buffer.
      rampup_steps: steps before compression engages. During ramp-up the
        transform emits DENSE momentum-corrected (heavyball) updates —
        i.e. it already acts as the momentum optimizer, matching the
        reference's DGCMomentum pre-rampup — and the residual stays
        empty (the reference's rampup_begin_step).
    """
    if not 0.0 <= sparsity < 1.0:
        raise ValueError(f"sparsity must be in [0, 1), got {sparsity}")

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return DGCState(step=jnp.zeros((), jnp.int32),
                        momentum=zeros,
                        residual=jax.tree.map(jnp.zeros_like, params))

    def _compress_leaf(u, v, step):
        """u: momentum buffer, v: accumulated velocity. Returns
        (sent, new_u, new_v) for one leaf."""
        n = v.size
        if n < 64 or sparsity == 0.0:
            return v, u, jnp.zeros_like(v)
        thresh = _topk_threshold(v.reshape(-1), 1.0 - sparsity, step)
        mask = (jnp.abs(v) >= thresh).astype(v.dtype)
        sent = v * mask
        keep = 1.0 - mask
        return sent, u * keep, v * keep

    def update_fn(updates, state, params=None):
        del params
        step = state.step + 1

        def corrected(u, g):
            return momentum * u + g

        u_new = jax.tree.map(corrected, state.momentum, updates)
        v_new = jax.tree.map(jnp.add, state.residual, u_new)

        compressed = jax.tree.map(lambda u, v: _compress_leaf(u, v, step),
                                  u_new, v_new)
        sent = jax.tree.map(lambda t: t[0], compressed,
                            is_leaf=lambda t: isinstance(t, tuple))
        u_kept = jax.tree.map(lambda t: t[1], compressed,
                              is_leaf=lambda t: isinstance(t, tuple))
        v_kept = jax.tree.map(lambda t: t[2], compressed,
                              is_leaf=lambda t: isinstance(t, tuple))

        in_rampup = step <= rampup_steps

        def select(dense, sparse):
            return jax.tree.map(
                lambda d, s: jnp.where(in_rampup, d, s), dense, sparse)

        # Ramp-up emits the momentum-CORRECTED update densely (u carries
        # across steps = heavyball momentum, matching the reference's
        # DGCMomentum staying a momentum optimizer pre-rampup); raw
        # pass-through would silently train momentum-free early epochs.
        out = select(u_new, sent)
        u_out = select(u_new, u_kept)
        v_out = select(jax.tree.map(jnp.zeros_like, v_new), v_kept)
        return out, DGCState(step=step, momentum=u_out, residual=v_out)

    return optax.GradientTransformation(init_fn, update_fn)


def sparse_psum(tree, axis_name: str, keep_frac: float = 0.01,
                axis_index_groups=None, wire: str = "fp32"):
    """Cross-worker gradient sum transferring only top-k per worker.

    For use INSIDE `shard_map` (where the author owns the collective):
    each worker selects its local top-k entries by magnitude, workers
    all_gather the (values, int32 indices) pairs — 2*k*4 bytes per leaf
    instead of n*4 — and every worker scatter-adds the gathered sparse
    contributions into a dense result. Entries below a worker's
    threshold are simply not contributed (callers wanting DGC's
    convergence behavior keep them in a local residual — the `dgc`
    transform's bookkeeping — and re-contribute later).

    ``wire='int8'`` additionally quantizes the top-k VALUES with the
    shared symmetric-int8 codec (ops/pack.py — the same scale/round
    math as the comm path's DCN leg and the fused optimizer's resident
    moments): k*(1+4) bytes per worker per leaf instead of k*(4+4),
    one fp32 scale riding along. Indices stay int32 — they address,
    they don't round.

    ``axis_index_groups`` scopes the reduction to subgroups of the axis
    exactly as in `lax.psum` — how a hierarchical decomposition keeps
    this wire on the slow cross-slice leg only
    (`mesh.dp_comm_groups`). The bucketed gradient path
    (`train/comm.py`) is this wire PLUS persistent error-feedback
    residuals and size-bucketed scheduling; use that for whole-step
    training, this for one-off tree reductions.

    Leaves with < 64 entries fall back to a dense `lax.psum`.
    Returns a tree of dense summed gradients, identical across workers
    (within each group, when grouped).
    """
    if wire not in ("fp32", "int8"):
        raise ValueError(f"wire must be 'fp32' or 'int8', got {wire!r}")

    def leaf(v):
        n = v.size
        if n < 64 or keep_frac >= 1.0:
            return lax.psum(v, axis_name,
                            axis_index_groups=axis_index_groups)
        k = max(1, int(round(n * keep_frac)))
        flat = v.reshape(-1)
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        vals = flat[idx]  # signed values at the top-|.| positions
        # (group, k) after gather — the ONLY cross-worker bytes
        if wire == "int8":
            # the shared gather wire (ops/pack.all_gather_int8): one
            # codec for this value wire, the comm DCN leg, and the MoE
            # dispatch — drift between them is structurally impossible
            from edl_tpu.ops.pack import all_gather_int8
            all_vals, _ = all_gather_int8(
                vals, axis_name, axis_index_groups=axis_index_groups)
            all_vals = all_vals.astype(v.dtype)
        else:
            all_vals = lax.all_gather(
                vals, axis_name, axis_index_groups=axis_index_groups)
        all_idx = lax.all_gather(idx, axis_name,
                                 axis_index_groups=axis_index_groups)
        dense = jnp.zeros_like(flat).at[all_idx.reshape(-1)].add(
            all_vals.reshape(-1))
        return dense.reshape(v.shape)

    return jax.tree.map(leaf, tree)


def compression_ratio(updates) -> float:
    """Fraction of nonzero entries in a (sparsified) update tree —
    host-side observability helper."""
    total = sum(leaf.size for leaf in jax.tree.leaves(updates))
    nonzero = sum(int(jnp.sum(leaf != 0)) for leaf in jax.tree.leaves(updates))
    return nonzero / max(total, 1)
