"""Record-serving data server + remote source client.

Working capability of the reference's WIP pod data-server pair
(utils/data_server.py:57-108 GetData servicer over a loader;
utils/distribute_reader.py:17-60 client fetching record batches from
remote data servers) — finished and re-designed for this stack: the
server exposes any pipeline *source* (`ArraySource`, `FileSource`) over
the binary tensor wire (data/tensor_wire.py), and `RemoteSource` IS a
source (`__len__` + `batch(indices)`), so a `DataLoader` consumes remote
records through the exact same deterministic shard-by-rank iteration it
uses for local data.

Use case (the C24 "leader-served file shards" story): rank 0 of a pod —
or a dedicated data pod — holds the dataset files and runs
`python -m edl_tpu.data.data_server --data-dir ... --port 23950`;
every trainer builds `DataLoader(RemoteSource("host:23950"), ...)`.
Determinism is preserved because index choice stays client-side; the
server is a stateless gather, so any number of trainers (and elastic
joins) can share one server without coordination.

Protocol (tensor-wire frames, meta carries control):
    -> {"op": "len"}                      <- {"ok": true, "n": N}
    -> {"op": "batch"} + idx tensor       <- {"ok": true} + record tensors
    -> {"op": "ping"}                     <- {"ok": true}
    errors:                               <- {"ok": false, "error": "..."}

r16 (edl-lint resource-lifecycle): ``RemoteSource`` kept a socket with
no teardown — it now has ``close()`` (``close_socket`` stays as the
internal reconnect path), and the CLI stops the server on ANY exit
path (try/finally), not just KeyboardInterrupt.
"""

from __future__ import annotations

import argparse
import socket
import struct
import threading
from typing import Any

import numpy as np

from edl_tpu.data.tensor_wire import (TensorWireError, recv_tensors,
                                         send_tensors)
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils.exceptions import EdlDataError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.data.data_server")


class DataServer:
    """Serve a source's records over the tensor wire (thread/conn)."""

    def __init__(self, source, host: str = "0.0.0.0", port: int = 0,
                 backlog: int = 64):
        self.source = source
        self._sock = socket.create_server((host, port), backlog=backlog)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conns: set[socket.socket] = set()  # guarded-by: _conns_lock
        self._conns_lock = threading.Lock()
        # serving counters (mutated under _conns_lock; the obs registry
        # reads the dict view at scrape time)
        self._requests = 0               # guarded-by: _conns_lock
        self._rows_served = 0            # guarded-by: _conns_lock
        self._obs = obs_metrics.register_stats("data_server", self.stats)

    def stats(self) -> dict:
        """Serving counters as a dict view (obs registry source)."""
        with self._conns_lock:
            return {"connections": len(self._conns),
                    "requests": self._requests,
                    "rows_served": self._rows_served,
                    "records": len(self.source)}

    def start(self) -> "DataServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="edl-data-server", daemon=True)
        self._accept_thread.start()
        log.info("data server on :%d (%d records)", self.port,
                 len(self.source))
        return self

    def stop(self) -> None:
        self._stop.set()
        # shutdown() first: close() alone leaves the fd (and the LISTEN
        # state) alive while the accept thread is blocked in accept(), so
        # the port could not be rebound until process exit.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
        # tear down live connections so the port is actually free
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        obs_metrics.unregister(self._obs)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    meta, tensors = recv_tensors(conn)
                except (TensorWireError, struct.error):
                    return  # disconnect or garbage: drop the connection
                try:
                    self._handle(conn, meta, tensors)
                except TensorWireError:
                    raise  # reply write failed — drop the connection
                except Exception as exc:  # noqa: BLE001 — any request
                    # failure (incl. a corrupt shard's BadZipFile) must
                    # reach the client as an error frame, not as a silent
                    # thread death + disconnect
                    send_tensors(conn, {"ok": False,
                                        "error": f"{type(exc).__name__}: "
                                                 f"{exc}"})
        except (OSError, TensorWireError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn, meta: dict[str, Any],
                tensors: dict[str, np.ndarray]) -> None:
        op = meta.get("op")
        with self._conns_lock:
            self._requests += 1
            if op == "batch":
                idx_t = tensors.get("idx")
                self._rows_served += (int(np.asarray(idx_t).size)
                                      if idx_t is not None else 0)
        if op == "ping":
            send_tensors(conn, {"ok": True})
        elif op == "len":
            send_tensors(conn, {"ok": True, "n": len(self.source)})
        elif op == "batch":
            idx = tensors.get("idx")
            if idx is None:
                raise EdlDataError("batch op needs an idx tensor")
            idx = np.asarray(idx, np.int64)
            n = len(self.source)
            if idx.ndim != 1 or (len(idx) and
                                 (idx.min() < 0 or idx.max() >= n)):
                raise EdlDataError(f"bad indices (n={n})")
            batch = self.source.batch(idx)
            send_tensors(conn, {"ok": True}, batch)
        else:
            raise EdlDataError(f"unknown op {op!r}")


class RemoteSource:
    """Client-side source over a DataServer endpoint.

    Satisfies the source protocol (`__len__`, `batch(idx)`), so it drops
    into `DataLoader` unchanged. One socket, guarded by a lock (the
    prefetch thread and the main thread may interleave); transient
    connection errors reconnect-and-retry once, then surface.
    """

    def __init__(self, endpoint: str, timeout: float = 30.0):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._n: int | None = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr,
                                                  timeout=self.timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self._sock

    def _call(self, meta: dict, tensors=None
              ) -> tuple[dict, dict[str, np.ndarray]]:
        with self._lock:
            for attempt in (0, 1):
                try:
                    sock = self._connect()
                    send_tensors(sock, meta, tensors)
                    rmeta, rtensors = recv_tensors(sock)
                    break
                except (OSError, TensorWireError):
                    self.close_socket()
                    if attempt:
                        raise
        if not rmeta.get("ok"):
            raise EdlDataError(
                f"data server error: {rmeta.get('error', '?')}")
        return rmeta, rtensors

    def close_socket(self) -> None:
        # holds no lock: called from _call (lock already held) and close()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        """Release the connection. The source stays usable — `_call`
        reconnects lazily — so an owner may close eagerly between
        epochs. (edl-lint resource-lifecycle: this is the teardown a
        kept socket requires; `close_socket` remains the internal
        reconnect path.)"""
        with self._lock:
            self.close_socket()

    def __len__(self) -> int:
        if self._n is None:
            self._n = int(self._call({"op": "len"})[0]["n"])
        return self._n

    def batch(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        _, tensors = self._call({"op": "batch"},
                                {"idx": np.asarray(idx, np.int64)})
        return tensors


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.data.data_server",
        description="Serve a directory of .npz shards to remote trainers")
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--pattern", default=".npz",
                        help="serve files whose name ends with this")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=23950)
    parser.add_argument("--cache-files", type=int, default=8)
    args = parser.parse_args(argv)

    import os

    from edl_tpu.data.pipeline import FileSource
    files = sorted(os.path.join(args.data_dir, f)
                   for f in os.listdir(args.data_dir)
                   if f.endswith(args.pattern))
    server = DataServer(FileSource(files, cache_files=args.cache_files),
                        host=args.host, port=args.port).start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
