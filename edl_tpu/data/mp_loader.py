"""Process-based loader worker pool with shared-memory batch hand-off.

Scales the host input plane past the GIL: N forked worker processes
each pull batch descriptors `(step, indices, pre-assigned per-sample RNG
seeds, batch-transform seed)` from their task queue, run source fetch +
sample/batch transforms (cv2 decode, crop, flip — the CPU-bound stage),
collate straight into a preallocated shared-memory slot
(data/shm_ring.py), and answer with a tiny metadata message.  The
parent reassembles results STRICTLY in step order and yields zero-copy
`np.ndarray` views over the slots — pixel bytes are written once by the
worker and read once by the consumer; no pickle, no extra copy.  This
is the multi-worker double-buffered feed of the reference's DALI reader
stack (example/collective/resnet50/dali.py) rebuilt for the
deterministic elastic contract.

Determinism: every random draw a step needs is made by the PARENT from
the per-(epoch, rank) generator before dispatch (DataLoader's per-step
seed protocol, data/pipeline.py), so worker scheduling cannot change
the stream — the mp path is bit-identical to the inline path, and an
elastic stop-resume replays the identical order from the step cursor.

Robustness contract:
- a dead/killed worker's in-flight descriptors are re-dispatched
  exactly ONCE to surviving workers (attempt-tagged: late messages from
  the corpse are ignored, the redispatched attempt owns the slot);
- a second death of the same descriptor, or the death of every worker,
  raises `EdlDataError` instead of hanging;
- a poisoned sample (transform/source exception) surfaces the worker's
  traceback on the consumer side at that step's turn, in order;
- `close()` (also driven by `DataLoader.close()`, context-manager exit
  and GC via `weakref.finalize`) joins the workers and unlinks every
  shm segment — abandoning an epoch iterator mid-epoch first drains
  in-flight slots so no worker is left writing into reclaimed memory.

Workers are started with the `fork` method so sources and transform
closures need no pickling (the reference's reader closures aren't
picklable either); workers never touch jax and cv2's own threading is
pinned off at import (data/image.py), which keeps fork safe.
"""

from __future__ import annotations

import collections
import queue
import signal
import time
import traceback
import warnings
from typing import Callable, Sequence

import multiprocessing as mp

import numpy as np

from edl_tpu.data import shm_ring
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.utils import config
from edl_tpu.utils.exceptions import EdlDataError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.data.mp_loader")

# Descriptor: (step, idx, sample_seeds | None, batch_seed | None)
Descriptor = tuple

_DRAIN_TIMEOUT = 30.0
_POLL = 0.05


class _WorkerEnv:
    """Everything a worker needs, inherited through fork (not pickled)."""

    def __init__(self, source, sample_transforms, transforms, ring,
                 task_qs, result_q, stop, emit_seed=False):
        self.source = source
        self.sample_transforms = sample_transforms
        self.transforms = transforms
        self.ring = ring
        self.task_qs = task_qs
        self.result_q = result_q
        self.stop = stop
        self.emit_seed = emit_seed


def _worker_main(env: _WorkerEnv, wid: int) -> None:
    # The parent owns ctrl-C: a KeyboardInterrupt mid-slot-write would
    # look like a poisoned sample instead of a clean shutdown.
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    # materialize_batch lives in pipeline.py (shared with the inline
    # path — the determinism contract is one function, not two copies).
    from edl_tpu.data.pipeline import materialize_batch

    task_q = env.task_qs[wid]
    while True:
        try:
            task = task_q.get(timeout=0.2)
        except queue.Empty:
            if env.stop.is_set():
                return
            continue
        if task is None:
            return
        step, attempt, slot, idx, sseeds, bseed = task
        try:
            batch = materialize_batch(env.source, idx,
                                      env.sample_transforms,
                                      env.transforms, sseeds, bseed,
                                      emit_seed=env.emit_seed)
            meta = shm_ring.write_batch(env.ring.buf(slot), batch)
            # meta=None: batch outgrew the slot (shape drift after the
            # sizing probe) — ship it pickled rather than fail; the
            # parent logs the slow path.
            spill = None if meta is not None else batch
            env.result_q.put((wid, step, attempt, slot, meta, spill, None))
        except BaseException:  # noqa: BLE001 — surfaced at the consumer
            env.result_q.put((wid, step, attempt, slot, None, None,
                              traceback.format_exc()))


class _Pending:
    __slots__ = ("desc", "wid", "attempt", "slot")

    def __init__(self, desc, wid, attempt, slot):
        self.desc = desc
        self.wid = wid
        self.attempt = attempt
        self.slot = slot


class MpLoaderPool:
    """Worker pool + shm ring; reused across epochs by one DataLoader.

    Args:
      source: the loader's source (fork-inherited; each worker keeps its
        own shard cache if the source has one).
      sample_transforms / transforms: the loader's transform stacks.
      num_workers: pool width (>= 1).
      slot_bytes: bytes one collated batch needs (size with a probe
        batch via `shm_ring.batch_nbytes`).
      n_slots: ring depth; default 2*workers+2 keeps every worker busy
        with one task queued each plus reorder slack.
      emit_seed: attach each descriptor's batch seed to its batch as a
        0-d uint32 "augment_seed" (the device-augmentation hand-off —
        DataLoader.emit_batch_seed).
    """

    def __init__(self, source, sample_transforms: Sequence[Callable],
                 transforms: Sequence[Callable], num_workers: int,
                 slot_bytes: int, n_slots: int | None = None,
                 emit_seed: bool = False):
        if num_workers < 1:
            raise EdlDataError(f"num_workers must be >= 1, got {num_workers}")
        n_slots = n_slots or 2 * num_workers + 2
        try:
            ctx = mp.get_context("fork")
        except ValueError as exc:  # pragma: no cover - non-POSIX hosts
            raise EdlDataError(
                "mp loader needs the fork start method (POSIX)") from exc
        self.ring = shm_ring.ShmRing(slot_bytes, n_slots)
        self._stop = ctx.Event()
        self._task_qs = [ctx.Queue() for _ in range(num_workers)]
        self._result_q = ctx.Queue()
        env = _WorkerEnv(source, list(sample_transforms), list(transforms),
                         self.ring, self._task_qs, self._result_q,
                         self._stop, emit_seed=emit_seed)
        self._procs = [ctx.Process(target=_worker_main, args=(env, wid),
                                   daemon=True,
                                   name=f"edl-mp-loader-{wid}")
                       for wid in range(num_workers)]
        with warnings.catch_warnings():
            # jax warns on any os.fork() because ITS threads could hold
            # locks across the fork; these workers never call into
            # jax/XLA (numpy + cv2 only, cv2 threading pinned off at
            # import), so the deadlock precondition can't arise.
            warnings.filterwarnings("ignore", message=".*os\\.fork\\(\\).*",
                                    category=RuntimeWarning)
            for p in self._procs:
                p.start()
        self._alive = set(range(num_workers))
        self._free: collections.deque[int] = collections.deque(
            range(n_slots))
        self.closed = False
        self.broken = False  # wedged drain: next epoch rebuilds the pool
        # input-plane accounting (consumer-thread-only counters; the
        # obs registry reads them as a scrape-time view)
        self.batches_served = 0
        self.redispatches = 0
        self.spills = 0
        self._obs = obs_metrics.register_stats("mp_loader", self.stats)

    def stats(self) -> dict:
        """Pool counters as a dict view (obs registry source)."""
        return {"workers": len(self._procs),
                "workers_alive": len(self._alive),
                "batches_served": self.batches_served,
                "redispatches": self.redispatches,
                "slot_spills": self.spills,
                "slots_free": len(self._free),
                "broken": self.broken}

    # -- liveness ----------------------------------------------------------

    def _check_workers(self, pending: dict[int, _Pending],
                       outstanding: dict[int, int],
                       redispatch: bool) -> None:
        """Detect deaths; re-dispatch (exactly once) or reclaim slots."""
        died = [wid for wid in self._alive
                if not self._procs[wid].is_alive()]
        if not died:
            return
        for wid in died:
            self._alive.discard(wid)
            log.warning("loader worker %d died (exitcode=%s)", wid,
                        self._procs[wid].exitcode)
        for step, pend in list(pending.items()):
            if pend.wid not in died:
                continue
            if not redispatch:
                # drain path: nobody will write this slot again
                self._free.append(pend.slot)
                outstanding.pop(step, None)
                del pending[step]
                continue
            if pend.attempt >= 1:
                raise EdlDataError(
                    f"loader batch {step} lost twice: worker {pend.wid} "
                    "died re-running a descriptor from an earlier dead "
                    "worker")
            if not self._alive:
                raise EdlDataError(
                    "all loader workers died; cannot re-dispatch "
                    f"in-flight batch {step}")
            pend.attempt += 1
            pend.wid = self._least_loaded(outstanding)
            self.redispatches += 1
            outstanding[step] = pend.wid
            step_, idx, sseeds, bseed = pend.desc
            self._task_qs[pend.wid].put(
                (step_, pend.attempt, pend.slot, idx, sseeds, bseed))
            log.warning("re-dispatched batch %d to worker %d", step,
                        pend.wid)

    def _least_loaded(self, outstanding: dict[int, int]) -> int:
        loads = collections.Counter(outstanding.values())
        return min(self._alive, key=lambda w: loads[w])

    # -- the ordered map ---------------------------------------------------

    def imap(self, descs: Sequence[Descriptor]):
        """Yield the batch of each descriptor, strictly in `descs` order.

        Yielded batches are zero-copy views over the ring; each stays
        valid until the NEXT yield (when its slot is recycled) — copy
        (or device_put) before advancing if a batch must outlive that.
        """
        if self.closed or self.broken:
            raise EdlDataError("mp loader pool is closed")
        todo = collections.deque(descs)
        pending: dict[int, _Pending] = {}
        outstanding: dict[int, int] = {}  # step -> wid (for load counts)
        results: dict[int, tuple] = {}
        order = collections.deque(d[0] for d in descs)
        prev_slot: int | None = None
        try:
            while order:
                # keep every free slot dispatched ahead of the consumer
                while todo and self._free and self._alive:
                    desc = todo.popleft()
                    slot = self._free.popleft()
                    wid = self._least_loaded(outstanding)
                    pending[desc[0]] = _Pending(desc, wid, 0, slot)
                    outstanding[desc[0]] = wid
                    step, idx, sseeds, bseed = desc
                    self._task_qs[wid].put((step, 0, slot, idx, sseeds,
                                            bseed))
                head = order[0]
                if head in results:
                    order.popleft()
                    slot, meta, spill, err = results.pop(head)
                    if prev_slot is not None:
                        self._free.append(prev_slot)
                        prev_slot = None
                    if err is not None:
                        self._free.append(slot)
                        raise EdlDataError(
                            f"loader worker failed on batch {head}:\n{err}")
                    self.batches_served += 1
                    if meta is None:
                        self._free.append(slot)  # spilled over the queue
                        self.spills += 1
                        yield spill
                    else:
                        prev_slot = slot
                        yield shm_ring.read_batch(self.ring.buf(slot),
                                                  meta)
                    continue
                self._pump(pending, outstanding, results, redispatch=True)
                if not self._alive and head not in results \
                        and head not in pending:
                    # head never dispatched and nobody left to take it
                    raise EdlDataError("all loader workers died")
        finally:
            if prev_slot is not None:
                self._free.append(prev_slot)
            # accepted-but-unyielded results (consumer closed early)
            # still own their slots
            for slot, _meta, _spill, _err in results.values():
                self._free.append(slot)
            results.clear()
            self._drain(pending, outstanding)

    def _pump(self, pending, outstanding, results, *, redispatch,
              timeout: float = _POLL) -> None:
        """Absorb one completion (or time out and check liveness)."""
        try:
            wid, step, attempt, slot, meta, spill, err = \
                self._result_q.get(timeout=timeout)
        except queue.Empty:
            self._check_workers(pending, outstanding, redispatch)
            return
        pend = pending.get(step)
        if pend is None or attempt != pend.attempt:
            # late echo from a dead worker's attempt (the redispatched
            # attempt owns the slot) — or a drain already reclaimed it
            return
        del pending[step]
        outstanding.pop(step, None)
        results[step] = (slot, meta, spill, err)
        if spill is not None:
            log.warning("batch %d outgrew its shm slot; shipped over "
                        "the queue (slow path)", step)

    def _drain(self, pending, outstanding) -> None:
        """Wait out in-flight work so every slot is reclaimed.

        Runs on normal epoch end AND when the consumer abandons the
        iterator mid-epoch (stop-resume): a worker may be mid-write, so
        slots cannot be recycled until its completion lands. A wedged
        worker trips the deadline; the pool is then torn down (killed,
        unlinked) and marked broken — the next epoch builds a fresh one.
        """
        deadline = time.monotonic() + _DRAIN_TIMEOUT
        while pending and time.monotonic() < deadline:
            results: dict[int, tuple] = {}
            try:
                self._pump(pending, outstanding, results, redispatch=False)
            except EdlDataError:  # worker died while draining
                continue
            for slot, _meta, _spill, _err in results.values():
                self._free.append(slot)
        if pending:
            log.error("mp loader drain timed out with %d batches in "
                      "flight; rebuilding the pool", len(pending))
            self.broken = True
            self.close()

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        """Stop workers (join, escalate to kill) and unlink the ring."""
        if self.closed:
            return
        self.closed = True
        self._stop.set()
        for q in self._task_qs:
            try:
                q.put_nowait(None)
            except Exception:  # noqa: BLE001 — teardown is best effort
                pass
        for p in self._procs:
            p.join(timeout=5.0)
        for p in self._procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
            if p.is_alive():  # pragma: no cover - SIGTERM ignored
                p.kill()
                p.join(timeout=2.0)
        for q in [*self._task_qs, self._result_q]:
            q.close()
            # don't let a queue feeder thread block interpreter exit
            q.cancel_join_thread()
        self.ring.close()
        obs_metrics.unregister(self._obs)


def default_num_workers() -> int:
    """The `EDL_TPU_LOADER_WORKERS` env contract (0 = inline/threaded)."""
    return max(0, config.env_int("EDL_TPU_LOADER_WORKERS", 0))


def probe_slot_bytes(batch: dict[str, np.ndarray]) -> int:
    """Ring slot size for a probe batch (re-exported for DataLoader)."""
    return shm_ring.batch_nbytes(batch)
