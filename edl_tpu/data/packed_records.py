"""Packed pre-decoded record format: the zero-host-transform feed path.

BENCH_r05 put the input plane's cost where the reference hid it behind
worker count (`loader_cores_to_feed_headline` ~= 7.8 — the host needed
~8 cores of JPEG decode + crop/flip to keep one chip busy; the
reference's DALI/`reader_cv2` stack papers over the same gap with
threads, example/collective/resnet50/dali.py).  A packed record file
removes the host work instead of parallelizing it:

- **decode once, offline**: the `pack` CLI eats any random-access source
  (a `JpegFileListSource` with a deterministic decode/resize, or `.npz`
  shards) and writes every sample PRE-DECODED at a fixed stride, so the
  train-time host never touches cv2 again;
- **O(1) mmap random access**: fields live as contiguous `(n, *shape)`
  tables at fixed offsets — row `i` of field `k` is one pointer
  computation into an `np.memmap`, so a shuffled epoch touches only the
  pages it reads (no shard LRU, no per-file grouping);
- **one gather per batch**: `PackedSource.batch(idx)` is a single
  `np.take` per field into a freshly-owned contiguous buffer — no
  per-sample Python loop, no second collation pass, and the result
  OWNS its memory (so `prefetch_to_device` places it without the
  defensive copy reserved for borrowed shm-ring views);
- **augmentation moves on-device** (`edl_tpu/ops/augment.py`): the
  loader ships raw bytes + the parent-drawn per-step seed and the
  jitted crop/flip/normalize runs on the accelerator, overlapping the
  step instead of burning host cores.

`PackedSource` implements the existing `__len__` + `batch(idx)` source
contract, so it flows through `materialize_batch`, the decode-thread
pool and the shm-ring mp path unchanged.

File layout (all little-endian, offsets 64-aligned):

    [0:8)      magic  b"EDLPACK1"
    [8:12)     uint32 header_len (JSON bytes; header block is 4 KiB)
    [12:12+L)  JSON header:
               {"version": 1, "n": <rows>,
                "fields": {key: {"shape": [...per-sample tail...],
                                 "dtype": "<numpy dtype str>",
                                 "offset": <bytes>}, ...}}
    [4096:...) field tables, each a contiguous (n, *shape) array

The trade is explicit: pre-decoded uint8 pixels are larger on disk than
JPEG (`bench.py` reports `loader_pack_ratio_bytes`), but disk bandwidth
is the cheap resource and host CPU the scarce one on a TPU VM.

CLI:

    python -m edl_tpu.data.packed_records pack --out train.pack \
        --jpeg-list train.txt --root data/ --size 224      # or
    python -m edl_tpu.data.packed_records pack --out train.pack \
        --npz-dir shards/                                  # or --npz f.npz
    python -m edl_tpu.data.packed_records info train.pack
    python -m edl_tpu.data.packed_records selftest
"""

from __future__ import annotations

import json
import os
import sys
from typing import Callable, Sequence

import numpy as np

from edl_tpu.utils.exceptions import EdlDataError

MAGIC = b"EDLPACK1"
_VERSION = 1
# Fixed header block: the JSON must fit under it so field offsets are
# independent of header growth (and page-aligned for the mmap).
HEADER_BLOCK = 4096
_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class PackedWriter:
    """Streaming writer for one packed record file.

    Row count and per-field (shape tail, dtype) are declared up front
    (every supported source knows its length), so field offsets are
    fixed before the first row lands and `add()` can interleave writes
    to each field's table.
    """

    def __init__(self, path: str,
                 n: int, fields: dict[str, tuple[tuple[int, ...], np.dtype]]):
        if n <= 0:
            raise EdlDataError(f"packed file needs n > 0 rows, got {n}")
        if not fields:
            raise EdlDataError("packed file needs at least one field")
        self.path = path
        self.n = n
        self._rows = 0
        self._fields: dict[str, dict] = {}
        off = HEADER_BLOCK
        for key in sorted(fields):
            shape, dtype = fields[key]
            dtype = np.dtype(dtype)
            row_bytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            self._fields[key] = {"shape": tuple(int(s) for s in shape),
                                 "dtype": dtype, "offset": off,
                                 "row_bytes": row_bytes}
            off = _align(off + n * row_bytes)
        header = {"version": _VERSION, "n": n,
                  "fields": {k: {"shape": list(f["shape"]),
                                 "dtype": f["dtype"].str,
                                 "offset": f["offset"]}
                             for k, f in self._fields.items()}}
        blob = json.dumps(header).encode()
        if len(blob) > HEADER_BLOCK - 12:
            raise EdlDataError(
                f"packed header {len(blob)}B exceeds the {HEADER_BLOCK}B "
                "header block (too many / too-long field keys)")
        self._f = open(path, "wb")
        try:
            self._f.write(MAGIC)
            self._f.write(np.uint32(len(blob)).tobytes())
            self._f.write(blob)
        except BaseException:
            self._f.close()
            raise

    def add(self, batch: dict[str, np.ndarray]) -> None:
        """Append `len(batch[k])` rows (every declared field required)."""
        sizes = {k: len(np.asarray(v)) for k, v in batch.items()}
        if set(sizes) != set(self._fields) or len(set(sizes.values())) != 1:
            raise EdlDataError(
                f"batch fields {sizes} do not match declared "
                f"{list(self._fields)}")
        rows = next(iter(sizes.values()))
        if self._rows + rows > self.n:
            raise EdlDataError(
                f"packed overflow: {self._rows}+{rows} rows > declared "
                f"{self.n}")
        for key, f in self._fields.items():
            arr = np.ascontiguousarray(batch[key], dtype=f["dtype"])
            if arr.shape[1:] != f["shape"]:
                raise EdlDataError(
                    f"field {key!r}: sample shape {arr.shape[1:]} != "
                    f"declared {f['shape']} (packed records are "
                    "fixed-stride — resize/crop to one shape when packing)")
            self._f.seek(f["offset"] + self._rows * f["row_bytes"])
            self._f.write(arr.tobytes())
        self._rows += rows

    def close(self) -> None:
        if self._f.closed:
            return
        try:
            if self._rows != self.n:
                raise EdlDataError(
                    f"packed file closed at {self._rows}/{self.n} rows")
            # materialize the full extent so a reader's size check holds
            # (alignment gaps between field tables are holes; the last
            # field's final add already wrote the true end)
            end = max(f["offset"] + self.n * f["row_bytes"]
                      for f in self._fields.values())
            self._f.truncate(end)
        finally:
            self._f.close()

    def __enter__(self) -> "PackedWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is not None:  # abort: leave no half-valid file behind
            self._f.close()
            try:
                os.unlink(self.path)
            except OSError:
                pass
            return
        self.close()


def read_header(path: str) -> dict:
    """Parse + validate a packed file's header; raises EdlDataError with
    a specific reason for anything short of a well-formed file (a
    truncated or corrupt file must never be read as garbage batches)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            head = f.read(12)
            if len(head) < 12 or head[:8] != MAGIC:
                raise EdlDataError(
                    f"{path}: not a packed records file (bad magic; "
                    "expected EDLPACK1)")
            hlen = int(np.frombuffer(head[8:12], np.uint32)[0])
            if not 0 < hlen <= HEADER_BLOCK - 12:
                raise EdlDataError(
                    f"{path}: corrupt packed header (length {hlen})")
            blob = f.read(hlen)
        if len(blob) != hlen:
            raise EdlDataError(f"{path}: truncated packed header")
        header = json.loads(blob)
    except EdlDataError:
        raise
    except (OSError, ValueError, UnicodeDecodeError) as exc:
        raise EdlDataError(f"{path}: corrupt packed header ({exc})") from exc
    if header.get("version") != _VERSION:
        raise EdlDataError(
            f"{path}: unsupported packed version {header.get('version')}")
    n = header.get("n")
    fields = header.get("fields")
    if not isinstance(n, int) or n <= 0 or not isinstance(fields, dict) \
            or not fields:
        raise EdlDataError(f"{path}: corrupt packed header (n/fields)")
    end = 0
    for key, f in fields.items():
        try:
            shape = tuple(int(s) for s in f["shape"])
            dtype = np.dtype(f["dtype"])
            off = int(f["offset"])
        except (KeyError, TypeError, ValueError) as exc:
            raise EdlDataError(
                f"{path}: corrupt packed field {key!r} ({exc})") from exc
        if off < HEADER_BLOCK or any(s <= 0 for s in shape):
            raise EdlDataError(
                f"{path}: corrupt packed field {key!r} (offset/shape)")
        row = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        end = max(end, off + n * row)
    if size < end:
        raise EdlDataError(
            f"{path}: truncated packed file ({size}B < expected {end}B) — "
            "repack; refusing to serve garbage batches")
    return header


class PackedSource:
    """Random-access source over one packed record file.

    Implements the loader source contract (`__len__` + `batch(idx) ->
    dict`), so it drops into `DataLoader` in every execution mode.
    Construction maps the field tables (`np.memmap` — reads only the
    header; sample pages fault in lazily on access) and `batch` is one
    `np.take` gather per field into a contiguous owned buffer: the host
    cost of a batch is a memcpy of exactly the requested rows.
    """

    def __init__(self, path: str):
        header = read_header(path)
        self.path = path
        self._n = header["n"]
        self._maps: dict[str, np.memmap] = {}
        for key in sorted(header["fields"]):
            f = header["fields"][key]
            self._maps[key] = np.memmap(
                path, dtype=np.dtype(f["dtype"]), mode="r",
                offset=int(f["offset"]),
                shape=(self._n,) + tuple(int(s) for s in f["shape"]))

    def __len__(self) -> int:
        return self._n

    @property
    def fields(self) -> dict[str, tuple[tuple[int, ...], np.dtype]]:
        return {k: (m.shape[1:], m.dtype) for k, m in self._maps.items()}

    def batch(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        idx = np.asarray(idx, dtype=np.intp)
        out = {}
        for key, mm in self._maps.items():
            buf = np.empty((len(idx),) + mm.shape[1:], mm.dtype)
            # one C-level gather per field, straight off the mapping —
            # no per-sample Python loop, no re-collation, and `buf`
            # owns its memory (prefetch_to_device places it copy-free)
            np.take(mm, idx, axis=0, out=buf)
            out[key] = buf
        return out


# -- packing ----------------------------------------------------------------

def pack_source(source, out_path: str, *, batch_size: int = 256,
                sample_transform: Callable | None = None,
                log: Callable[[str], None] | None = None) -> dict:
    """Pack any random-access source into `out_path`.

    Without `sample_transform` the source's `batch(idx)` dicts are
    written as-is (the npz path — dtypes/shapes preserved).  With it,
    `source.samples(idx)` records are mapped through the transform
    (e.g. `eval_image_transform`: decode + resize-short + center-crop
    to ONE fixed shape) and collated — the pack step runs the decode
    exactly once so train time never does.
    """
    n = len(source)
    if n == 0:
        raise EdlDataError("cannot pack an empty source")

    def get_batch(lo: int, hi: int) -> dict[str, np.ndarray]:
        idx = np.arange(lo, hi)
        if sample_transform is None:
            return source.batch(idx)
        done = [sample_transform(s, None) for s in source.samples(idx)]
        return {k: np.stack([d[k] for d in done]) for k in done[0]}

    first = get_batch(0, min(batch_size, n))
    fields = {k: (np.asarray(v).shape[1:], np.asarray(v).dtype)
              for k, v in first.items()}
    with PackedWriter(out_path, n, fields) as w:
        w.add(first)
        for lo in range(batch_size, n, batch_size):
            w.add(get_batch(lo, min(lo + batch_size, n)))
            if log is not None:
                log(f"packed {min(lo + batch_size, n)}/{n} rows")
    return {"n": n,
            "fields": {k: (list(s), d.str) for k, (s, d) in fields.items()},
            "bytes": os.path.getsize(out_path)}


def pack_jpeg_list(list_file: str, root: str, out_path: str, *,
                   size: int = 224, short: int | None = None,
                   batch_size: int = 256,
                   log: Callable[[str], None] | None = None) -> dict:
    """Pack a `<path> <label>` JPEG file list: deterministic decode +
    resize-short + center-crop to (size, size, 3) uint8 — train-time
    augmentation (random crop/flip) moves ON DEVICE (`ops/augment.py`),
    so the pack step bakes only the deterministic geometry."""
    from edl_tpu.data.image import JpegFileListSource, eval_image_transform
    src = JpegFileListSource(list_file, root=root)
    t = eval_image_transform(size, short=short or size * 8 // 7)
    return pack_source(src, out_path, batch_size=batch_size,
                       sample_transform=t, log=log)


def pack_npz(files: Sequence[str], out_path: str, *,
             batch_size: int = 256,
             log: Callable[[str], None] | None = None) -> dict:
    """Pack .npz shard files (FileSource order, dtypes preserved)."""
    from edl_tpu.data.pipeline import FileSource
    return pack_source(FileSource(files), out_path, batch_size=batch_size,
                       log=log)


# -- CLI --------------------------------------------------------------------

def _selftest() -> int:
    """CI smoke: pack a tiny synthetic dataset, prove round-trip byte
    equality, mode-invariant streams (inline vs mp) with emitted device
    seeds, and corrupt-file rejection.  numpy-only (no jax, no cv2) so
    it runs anywhere the loader does."""
    import shutil
    import tempfile

    from edl_tpu.data.pipeline import DataLoader

    d = tempfile.mkdtemp(prefix="edl-pack-selftest-")
    try:
        rng = np.random.default_rng(0)
        files = []
        for i in range(2):
            path = os.path.join(d, f"train-{i}.npz")
            np.savez(path,
                     image=rng.integers(0, 256, size=(24, 8, 8, 3),
                                        dtype=np.uint8),
                     label=rng.integers(0, 10, size=24).astype(np.int32))
            files.append(path)
        out = os.path.join(d, "train.pack")
        info = pack_npz(files, out, batch_size=7)
        src = PackedSource(out)
        from edl_tpu.data.pipeline import FileSource
        ref = FileSource(files)
        idx = np.arange(len(src))
        got, want = src.batch(idx), ref.batch(idx)
        for k in want:
            if not np.array_equal(got[k], want[k]):
                print(f"FAIL round-trip field {k}")
                return 1
        print(f"PASS pack round-trip ({info['n']} rows, "
              f"{info['bytes']}B)")
        with DataLoader(src, 8, seed=3, emit_batch_seed=True) as inline:
            a = [{k: np.array(v) for k, v in b.items()}
                 for b in inline.epoch(1)]
        with DataLoader(src, 8, seed=3, emit_batch_seed=True,
                        num_workers=1) as mp:
            b = [{k: np.array(v) for k, v in bb.items()}
                 for bb in mp.epoch(1)]
        for x, y in zip(a, b):
            for k in x:
                if not np.array_equal(x[k], y[k]):
                    print(f"FAIL mode invariance field {k}")
                    return 1
        if "augment_seed" not in a[0]:
            print("FAIL emitted seed missing")
            return 1
        print(f"PASS mode-invariant stream ({len(a)} batches, seeds "
              "emitted)")
        bad = os.path.join(d, "bad.pack")
        with open(out, "rb") as f, open(bad, "wb") as g:
            g.write(f.read(HEADER_BLOCK + 100))  # truncate the tables
        try:
            PackedSource(bad)
        except EdlDataError as exc:
            print(f"PASS truncated file rejected ({exc})")
        else:
            print("FAIL truncated file accepted")
            return 1
        return 0
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main(argv=None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.data.packed_records")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("pack", help="pack a jpeg list / npz shards")
    p.add_argument("--out", required=True)
    p.add_argument("--jpeg-list", help="'<path> <label>' file list")
    p.add_argument("--root", default="", help="jpeg path root")
    p.add_argument("--size", type=int, default=224,
                   help="packed image side (decode + resize-short + "
                        "center-crop)")
    p.add_argument("--short", type=int, default=None,
                   help="resize-short target before the crop "
                        "(default size*8/7)")
    p.add_argument("--npz-dir", help="directory of train-*.npz shards")
    p.add_argument("--npz", nargs="+", help="explicit npz shard files")
    p.add_argument("--batch", type=int, default=256)
    i = sub.add_parser("info", help="print a packed file's header")
    i.add_argument("path")
    sub.add_parser("selftest", help="pack+read smoke on synthetic data")
    args = parser.parse_args(argv)

    if args.cmd == "selftest":
        return _selftest()
    if args.cmd == "info":
        header = read_header(args.path)
        header["bytes"] = os.path.getsize(args.path)
        print(json.dumps(header, indent=2))
        return 0
    chosen = [x for x in (args.jpeg_list, args.npz_dir, args.npz) if x]
    if len(chosen) != 1:
        parser.error("pack needs exactly one of --jpeg-list / --npz-dir "
                     "/ --npz")
    if args.jpeg_list:
        info = pack_jpeg_list(args.jpeg_list, args.root, args.out,
                              size=args.size, short=args.short,
                              batch_size=args.batch, log=print)
    else:
        files = args.npz or sorted(
            os.path.join(args.npz_dir, f)
            for f in os.listdir(args.npz_dir)
            if f.startswith("train-") and f.endswith(".npz"))
        if not files:
            parser.error(f"no train-*.npz shards under {args.npz_dir}")
        info = pack_npz(files, args.out, batch_size=args.batch, log=print)
    print(json.dumps({"out": args.out, **info}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
