from edl_tpu.data.pipeline import (ArraySource, DataLoader, epoch_indices,
                                   prefetch, prefetch_to_device)

__all__ = ["ArraySource", "DataLoader", "epoch_indices", "prefetch",
           "prefetch_to_device"]
