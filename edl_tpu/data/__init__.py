from edl_tpu.data.data_server import DataServer, RemoteSource
from edl_tpu.data.pipeline import (ArraySource, DataLoader, FileSource,
                                   epoch_indices, prefetch,
                                   prefetch_to_device)
from edl_tpu.data.task_loader import (TaskDataLoader, npz_loader,
                                      text_loader)
from edl_tpu.data.task_master import TaskMaster, file_list_specs

__all__ = ["ArraySource", "DataLoader", "DataServer", "FileSource",
           "RemoteSource", "epoch_indices", "prefetch",
           "prefetch_to_device", "TaskDataLoader", "TaskMaster",
           "file_list_specs", "npz_loader", "text_loader"]
