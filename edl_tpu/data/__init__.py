from edl_tpu.data.data_server import DataServer, RemoteSource
from edl_tpu.data.image import (JpegFileListSource, decode_jpeg,
                                encode_jpeg, eval_image_transform,
                                train_image_transform)
from edl_tpu.data.packed_records import (PackedSource, PackedWriter,
                                         pack_jpeg_list, pack_npz,
                                         pack_source)
from edl_tpu.data.pipeline import (ArraySource, DataLoader, FileSource,
                                   epoch_indices, prefetch,
                                   prefetch_to_device)
from edl_tpu.data.task_loader import (TaskDataLoader, npz_loader,
                                      text_loader)
from edl_tpu.data.task_master import TaskMaster, file_list_specs

__all__ = ["ArraySource", "DataLoader", "DataServer", "FileSource",
           "JpegFileListSource", "PackedSource", "PackedWriter",
           "RemoteSource", "decode_jpeg", "encode_jpeg", "epoch_indices",
           "eval_image_transform", "pack_jpeg_list", "pack_npz",
           "pack_source", "prefetch", "prefetch_to_device",
           "train_image_transform", "TaskDataLoader", "TaskMaster",
           "file_list_specs", "npz_loader", "text_loader"]
