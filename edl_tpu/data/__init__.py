"""Input-plane package.

Lazy by design: importing ``edl_tpu.data`` (or any of its jax-free
submodules — ``tensor_wire``, ``shm_ring``, ``data_server``) must not
pull jax or cv2. ``pipeline``/``image``/``task_loader`` import jax or
cv2 at module load, and the distill serving plane reaches
``data.tensor_wire`` from processes that deliberately never load the
accelerator stack (the jax-free-import contract pinned by
``test_distill_import_is_jax_free``). The package namespace therefore
resolves its public names through ``__getattr__`` (PEP 562), exactly
like ``edl_tpu.distill`` does — the first *use* of ``DataLoader``
imports pipeline, not the package import itself.
"""

_EXPORTS = {
    "DataServer": "edl_tpu.data.data_server",
    "RemoteSource": "edl_tpu.data.data_server",
    "JpegFileListSource": "edl_tpu.data.image",
    "decode_jpeg": "edl_tpu.data.image",
    "encode_jpeg": "edl_tpu.data.image",
    "eval_image_transform": "edl_tpu.data.image",
    "train_image_transform": "edl_tpu.data.image",
    "PackedSource": "edl_tpu.data.packed_records",
    "PackedWriter": "edl_tpu.data.packed_records",
    "pack_jpeg_list": "edl_tpu.data.packed_records",
    "pack_npz": "edl_tpu.data.packed_records",
    "pack_source": "edl_tpu.data.packed_records",
    "ArraySource": "edl_tpu.data.pipeline",
    "DataLoader": "edl_tpu.data.pipeline",
    "FileSource": "edl_tpu.data.pipeline",
    "epoch_indices": "edl_tpu.data.pipeline",
    "prefetch": "edl_tpu.data.pipeline",
    "prefetch_to_device": "edl_tpu.data.pipeline",
    "TaskDataLoader": "edl_tpu.data.task_loader",
    "npz_loader": "edl_tpu.data.task_loader",
    "text_loader": "edl_tpu.data.task_loader",
    "TaskMaster": "edl_tpu.data.task_master",
    "file_list_specs": "edl_tpu.data.task_master",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module 'edl_tpu.data' has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(module), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
