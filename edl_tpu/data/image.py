"""Host-side JPEG decode + augmentation input plane.

Capability of the reference's cv2 reader stack — the file-list reader
with a decode thread pool (example/collective/resnet50/utils/
reader_cv2.py:27-105, `xmap_readers(image_mapper, _reader, threads,
buf_size)`) and its transform set (example/collective/resnet50/utils/
img_tool.py:34-69 random-resized-crop with scale/ratio sampling,
:128-131 horizontal flip p=0.5, :77-103 resize-short + center-crop for
eval) — re-designed for this stack's deterministic elastic contract:

- **uint8 NHWC RGB out, normalize ON DEVICE.** The reference converts
  to float32 and normalizes per channel on the host
  (img_tool.py:133-140); here the host ships 1 byte per channel and the
  jitted step does mean/std math on chip (the DALI recipe — shipping
  float32 pixels quadruples H2D bytes, and H2D is the scarce resource
  on a TPU VM).
- **Determinism under a thread pool.** The reference's xmap runs
  `order=False` with a shared `random` module — worker scheduling
  changes the stream, so an elastic restart cannot replay it. Here
  every sample's augmentation RNG seed is PRE-ASSIGNED from the
  loader's per-(epoch, rank) generator before the pool touches the
  batch, so any thread interleaving produces bit-identical batches
  (the D-invariant that makes the <1%-acc-over-resizes clause
  testable).
- Transforms are per-SAMPLE callables `(sample_dict, rng) -> dict`
  (images arrive in variable sizes; batch-level transforms only exist
  after collation). `DataLoader(sample_transforms=...)` runs them under
  its decode pool — see data/pipeline.py.
"""

from __future__ import annotations

import math
import os
from typing import Sequence

import numpy as np

from edl_tpu.utils.exceptions import EdlDataError

try:  # cv2 is the decode engine (same as the reference's reader)
    import cv2

    cv2.setNumThreads(0)  # the loader's pool owns parallelism, not cv2
except ImportError:  # pragma: no cover - cv2 is baked into the image
    cv2 = None


def _require_cv2() -> None:
    if cv2 is None:
        raise EdlDataError("cv2 is required for the JPEG input plane")


def decode_jpeg(buf: bytes | np.ndarray) -> np.ndarray:
    """JPEG/PNG bytes -> RGB uint8 HWC (reference decodes BGR via
    cv2.imread then flips to RGB at normalize time, img_tool.py:133)."""
    _require_cv2()
    arr = np.frombuffer(buf, np.uint8) if isinstance(buf, (bytes, bytearray)) \
        else np.asarray(buf, np.uint8)
    img = cv2.imdecode(arr, cv2.IMREAD_COLOR)
    if img is None:
        raise EdlDataError("cv2 could not decode image bytes")
    return img[:, :, ::-1]  # BGR -> RGB


def encode_jpeg(img: np.ndarray, quality: int = 90) -> bytes:
    """RGB uint8 HWC -> JPEG bytes (synthetic-dataset / test helper)."""
    _require_cv2()
    ok, buf = cv2.imencode(".jpg", np.asarray(img)[:, :, ::-1],
                           [int(cv2.IMWRITE_JPEG_QUALITY), quality])
    if not ok:
        raise EdlDataError("cv2 could not encode image")
    return bytes(buf)


def random_resized_crop(img: np.ndarray, rng: np.random.Generator,
                        size: int, scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3)
                        ) -> np.ndarray:
    """The Inception-style crop of the reference (img_tool.py:34-69):
    sample aspect = sqrt(U(ratio)), bound the area scale so the crop
    fits, take a uniform window, resize to (size, size)."""
    _require_cv2()
    h, w = img.shape[:2]
    aspect = math.sqrt(rng.uniform(*ratio))
    cw, ch = aspect, 1.0 / aspect
    bound = min((w / h) / (cw * cw), (h / w) / (ch * ch))
    scale_max = min(scale[1], bound)
    scale_min = min(scale[0], bound)
    target_area = h * w * rng.uniform(scale_min, scale_max)
    target = math.sqrt(target_area)
    # int() truncation keeps the window inside the image (the bound
    # guarantees the exact-real window fits); clamp for 1-pixel edges
    cw = min(max(1, int(target * cw)), w)
    ch = min(max(1, int(target * ch)), h)
    i = rng.integers(0, h - ch + 1)
    j = rng.integers(0, w - cw + 1)
    return cv2.resize(img[i:i + ch, j:j + cw], (size, size),
                      interpolation=cv2.INTER_LINEAR)


def random_flip_lr_sample(img: np.ndarray, rng: np.random.Generator
                          ) -> np.ndarray:
    """Horizontal flip with p=0.5 (img_tool.py:128-129)."""
    return img[:, ::-1] if rng.random() < 0.5 else img


def random_rotate(img: np.ndarray, rng: np.random.Generator,
                  max_deg: float = 10.0) -> np.ndarray:
    """Rotate about the center by U(-max_deg, max_deg)
    (img_tool.py:24-31 `rotate_image`, the reference's --rotate flag)."""
    _require_cv2()
    h, w = img.shape[:2]
    angle = float(rng.uniform(-max_deg, max_deg))
    m = cv2.getRotationMatrix2D((w / 2, h / 2), angle, 1.0)
    return cv2.warpAffine(img, m, (w, h))


def resize_short(img: np.ndarray, target: int) -> np.ndarray:
    """Scale so the SHORT side equals target (img_tool.py:77-86)."""
    _require_cv2()
    h, w = img.shape[:2]
    percent = target / min(h, w)
    return cv2.resize(img, (int(round(w * percent)),
                            int(round(h * percent))),
                      interpolation=cv2.INTER_LINEAR)


def center_crop(img: np.ndarray, size: int) -> np.ndarray:
    """Central (size, size) window (img_tool.py:89-103 center=True)."""
    h, w = img.shape[:2]
    i = (h - size) // 2
    j = (w - size) // 2
    return img[i:i + size, j:j + size]


def train_image_transform(size: int = 224,
                          scale: tuple[float, float] = (0.08, 1.0),
                          ratio: tuple[float, float] = (3 / 4, 4 / 3),
                          rotate: bool = False,
                          key: str = "jpeg", out: str = "image"):
    """Per-sample train path: decode -> [rotate] -> random-resized-crop
    -> flip (the order of process_image, img_tool.py:119-131; rotate is
    the reference's off-by-default --rotate flag).

    Returns a `(sample, rng) -> sample` callable for
    `DataLoader(sample_transforms=...)`. Output is uint8 (size, size, 3)
    RGB under `out`; the raw bytes key is dropped."""

    def transform(sample: dict, rng: np.random.Generator) -> dict:
        img = decode_jpeg(sample[key])
        if rotate:
            img = random_rotate(img, rng)
        img = random_resized_crop(img, rng, size, scale, ratio)
        img = random_flip_lr_sample(img, rng)
        rest = {k: v for k, v in sample.items() if k != key}
        return {**rest, out: np.ascontiguousarray(img)}

    return transform


def eval_image_transform(size: int = 224, short: int = 256,
                         key: str = "jpeg", out: str = "image"):
    """Per-sample eval path: decode -> resize-short -> center-crop
    (img_tool.py:134-137, resize_short_size=256 for crop 224)."""

    def transform(sample: dict, rng: np.random.Generator) -> dict:
        del rng  # eval is augmentation-free
        img = decode_jpeg(sample[key])
        img = center_crop(resize_short(img, short), size)
        rest = {k: v for k, v in sample.items() if k != key}
        return {**rest, out: np.ascontiguousarray(img)}

    return transform


class JpegFileListSource:
    """Random-access source over a `path label` file list of JPEGs.

    The reference's file-list contract (reader_cv2.py:39-88: one
    `<relpath> <int label>` pair per line, paths relative to a data
    root). `samples(idx)` returns per-sample dicts with RAW bytes —
    decode happens in the loader's transform pool, where it
    parallelizes; this class only does I/O.
    """

    def __init__(self, list_file: str | None = None, root: str = "",
                 entries: Sequence[tuple[str, int]] | None = None):
        if (list_file is None) == (entries is None):
            raise EdlDataError(
                "JpegFileListSource needs exactly one of list_file/entries")
        if list_file is not None:
            entries = []
            with open(list_file) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    path, label = line.rsplit(None, 1)
                    entries.append((path, int(label)))
        if not entries:
            raise EdlDataError("empty JPEG file list")
        self.root = root
        self.entries = list(entries)

    def __len__(self) -> int:
        return len(self.entries)

    def samples(self, idx: np.ndarray) -> list[dict]:
        out = []
        for i in idx:
            path, label = self.entries[int(i)]
            with open(os.path.join(self.root, path), "rb") as f:
                out.append({"jpeg": f.read(),
                            "label": np.int32(label)})
        return out


def make_synthetic_jpeg_dataset(directory: str, n: int, *,
                                classes: int = 1000,
                                hw: tuple[int, int] = (360, 480),
                                seed: int = 0,
                                quality: int = 90) -> str:
    """Write n random JPEGs + train.txt under `directory`; returns the
    list-file path. Sizes jitter around `hw` so crop paths see varied
    shapes (real ImageNet is variable-sized)."""
    os.makedirs(directory, exist_ok=True)
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        h = int(hw[0] * rng.uniform(0.8, 1.25))
        w = int(hw[1] * rng.uniform(0.8, 1.25))
        img = rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8)
        name = f"img_{i:06d}.jpg"
        with open(os.path.join(directory, name), "wb") as f:
            f.write(encode_jpeg(img, quality))
        lines.append(f"{name} {int(rng.integers(0, classes))}")
    list_file = os.path.join(directory, "train.txt")
    with open(list_file, "w") as f:
        f.write("\n".join(lines) + "\n")
    return list_file
