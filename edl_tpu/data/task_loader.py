"""File-backed, elastically-dispensed batch iteration.

The consumer half of the task-dispenser story — the working replacement
for the reference's WIP DataLoader-over-data-server (collective/
dataloader.py:26-120 pulls file shards from the leader and skips
already-processed records; utils/data_server.py:57-108 serves records):
each pod's `TaskDataLoader` leases file-shard tasks from the `TaskMaster`
table, loads the file on host, yields fixed-size batches, and marks the
task done — so a killed pod's in-flight shards are re-dispensed to
survivors after the lease timeout, completed shards are never re-read,
and "which records are trained" is exactly the store's task table.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Sequence

import numpy as np

from edl_tpu.data.task_master import Task, TaskMaster
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.data.task_loader")


def npz_loader(spec: dict) -> dict[str, np.ndarray]:
    """Load {"file": x.npz[, "start", "stop"]} into a dict of arrays."""
    with np.load(spec["file"]) as data:
        arrays = {k: data[k] for k in data.files}
    if "start" in spec:
        arrays = {k: v[spec["start"]:spec["stop"]] for k, v in arrays.items()}
    return arrays


def text_loader(spec: dict) -> dict[str, np.ndarray]:
    """Load a text file into {"line": bytes array} (reference
    TxtDataReader, collective/dataset.py:33)."""
    with open(spec["file"], "rb") as f:
        lines = f.read().splitlines()
    if "start" in spec:
        lines = lines[spec["start"]:spec["stop"]]
    return {"line": np.array(lines, dtype=object)}


class TaskDataLoader:
    """Iterate batches of the epoch's dispensed file shards.

    Args:
      master: the TaskMaster (one per pod, distinct owners).
      loader_fn: spec dict -> dict of equal-length arrays (host).
      batch_size: rows per yielded batch.
      drop_remainder: drop the file's trailing partial batch.
      transforms: (batch, np.random.Generator) -> batch host hooks.
      poll: seconds between get_task retries while peers hold leases.
      heartbeat_every: extend the task lease after this many seconds of
        yielding (long files vs short lease timeouts).
    """

    def __init__(self, master: TaskMaster, loader_fn: Callable[[dict], dict],
                 batch_size: int, *, drop_remainder: bool = False,
                 transforms: Sequence[Callable] = (), poll: float = 0.2,
                 seed: int = 0, heartbeat_every: float = 10.0):
        self.master = master
        self.loader_fn = loader_fn
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.transforms = list(transforms)
        self.poll = poll
        self.seed = seed
        self.heartbeat_every = heartbeat_every
        self.tasks_completed = 0
        self.tasks_lost = 0

    def _task_batches(self, task: Task, rng) -> Iterator[dict]:
        arrays = self.loader_fn(task.spec)
        n = len(next(iter(arrays.values())))
        stop = (n // self.batch_size * self.batch_size
                if self.drop_remainder else n)
        last_beat = time.monotonic()
        for lo in range(0, stop, self.batch_size):
            hi = min(lo + self.batch_size, stop)
            batch = {k: v[lo:hi] for k, v in arrays.items()}
            for t in self.transforms:
                batch = t(batch, rng)
            if time.monotonic() - last_beat > self.heartbeat_every:
                if not self.master.heartbeat(task):
                    # Lease lost (e.g. we stalled past the timeout and the
                    # shard was re-dispensed): stop contributing this task.
                    return
                last_beat = time.monotonic()
            yield batch

    def epoch(self, epoch: int) -> Iterator[dict]:
        """Yield batches until the epoch's task table is drained."""
        rng = np.random.default_rng(self.seed * 1_000_003 + epoch)
        while True:
            task = self.master.get_task()
            if task is None:
                if self.master.epoch_done():
                    return
                time.sleep(self.poll)
                continue
            try:
                yield from self._task_batches(task, rng)
            except Exception as exc:
                self.master.errored(task, f"{type(exc).__name__}: {exc}")
                raise
            if self.master.finished(task):
                self.tasks_completed += 1
            else:
                self.tasks_lost += 1

    def __call__(self, epoch: int) -> Iterator[dict]:
        # TrainLoop data_fn signature.
        return self.epoch(epoch)
