"""Ring of preallocated shared-memory batch slots for the mp loader.

The hand-off half of the multi-process input plane (data/mp_loader.py):
worker processes write collated batches straight into a slot's mapping
and send only a tiny (key, shape, dtype, offset) descriptor back over
the result queue — pixel bytes never ride a pipe and never get pickled.
The parent wraps the slot in `np.ndarray` views (zero-copy) and recycles
the slot once the consumer has moved past the batch.

Slots are plain `multiprocessing.shared_memory` segments sized for one
collated batch each.  The ring is created by the PARENT before the
workers fork, so children inherit the mappings directly; only the
parent ever `unlink()`s.  `close()` is idempotent and tolerates live
numpy views (the consumer may still hold the last batch): the mapping
then stays alive until those views die, but the /dev/shm name is gone —
teardown never leaks a segment.
"""

from __future__ import annotations

import itertools
import os
from multiprocessing import shared_memory

import numpy as np

_ring_ids = itertools.count()

# 64-byte alignment for every array inside a slot: keeps rows cache-line
# aligned and lets downstream consumers (device DMA, vectorized numpy)
# treat views like freshly allocated buffers.
_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def batch_nbytes(batch: dict[str, np.ndarray]) -> int:
    """Aligned bytes one slot needs to hold `batch` (the sizing probe)."""
    return sum(_align(np.asarray(v).nbytes) for v in batch.values())


def _view(buf, shape, dtype, offset) -> np.ndarray:
    # np.frombuffer (NOT np.ndarray(buffer=...)): frombuffer registers a
    # real buffer export on the memoryview, so SharedMemory.close() with
    # a live view raises BufferError instead of silently unmapping the
    # pages under it (a segfault on next read). ShmRing.close() catches
    # that and lets the mapping die with the last view.
    dtype = np.dtype(dtype)
    count = int(np.prod(shape)) if shape else 1
    return np.frombuffer(buf, dtype, count=count,
                         offset=offset).reshape(shape)


def write_batch(buf, batch: dict[str, np.ndarray]
                ) -> list[tuple[str, tuple[int, ...], str, int]] | None:
    """Write `batch` into a slot buffer; returns the view metadata
    [(key, shape, dtype.str, offset)] or None if the batch does not fit
    (the caller falls back to shipping it over the queue)."""
    offset = 0
    meta = []
    cap = len(buf)
    for k in sorted(batch):
        # ascontiguousarray only when needed: it promotes 0-d to (1,)
        # (the device-augment seed is a 0-d uint32 and must round-trip
        # shape-intact — same guard as tensor_wire.send_tensors)
        arr = np.asarray(batch[k])
        if not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        if offset + arr.nbytes > cap:
            return None
        _view(buf, arr.shape, arr.dtype, offset)[...] = arr
        meta.append((k, arr.shape, arr.dtype.str, offset))
        offset = _align(offset + arr.nbytes)
    return meta


def read_batch(buf, meta) -> dict[str, np.ndarray]:
    """Zero-copy np.ndarray views over a slot from `write_batch` meta.

    Views alias the slot: they are valid until the slot is recycled
    (i.e. until the consumer advances past this batch) — copy if kept.
    """
    return {k: _view(buf, shape, dtype, off)
            for k, shape, dtype, off in meta}


class ShmRing:
    """N preallocated shared-memory slots, parent-owned.

    The parent creates the ring before forking workers; slot acquisition
    / recycling is the parent's job (mp_loader tracks which slot each
    dispatched descriptor owns), so the ring itself is just storage +
    teardown.
    """

    def __init__(self, slot_bytes: int, n_slots: int):
        if slot_bytes <= 0 or n_slots <= 0:
            raise ValueError(f"bad ring: {n_slots} x {slot_bytes}B")
        self.slot_bytes = _align(slot_bytes)
        self.slots: list[shared_memory.SharedMemory] = []
        rid = next(_ring_ids)
        try:
            for i in range(n_slots):
                self.slots.append(shared_memory.SharedMemory(
                    create=True, size=self.slot_bytes,
                    name=f"edl_mp_{os.getpid()}_{rid}_{i}"))
        except BaseException:
            self.close()
            raise
        self._closed = False

    def __len__(self) -> int:
        return len(self.slots)

    def buf(self, slot: int):
        return self.slots[slot].buf

    def close(self) -> None:
        """Unlink every segment (idempotent; safe with live views).

        unlink() removes the /dev/shm name immediately — the memory
        itself lives until the last mapping (parent views, worker
        processes) drops, so consumers holding the final batch keep
        valid data while the leak-check surface stays clean.
        """
        for shm in self.slots:
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
            try:
                shm.close()
            except BufferError:
                # A consumer still holds a zero-copy view over this
                # slot. The unlink above already dropped the name; hand
                # the mapping's lifetime to the view chain (the mmap
                # unmaps when the last view dies) and close the fd now —
                # leaving close() to retry in __del__ would just raise
                # the same BufferError unraisably at GC.  The surgery
                # pokes SharedMemory privates whose names/layout drift
                # across CPython versions, so any miss degrades to
                # leaving teardown to the view chain (nothing leaks:
                # the /dev/shm name is gone), never to a crash.
                try:
                    fd = shm._fd
                    shm._buf = None
                    shm._mmap = None
                    if isinstance(fd, int) and fd >= 0:
                        os.close(fd)
                        shm._fd = -1
                except (AttributeError, OSError):
                    pass
        self._closed = True
