"""Binary tensor framing for the teacher RPC data plane.

Frame = 4-byte magic ``EDT1`` + uint32 header length + UTF-8 JSON header +
raw little-endian tensor payload (buffers concatenated in header order):

    header = {"meta": {...}, "tensors": [{"name", "dtype", "shape"}]}

JSON carries control, raw bytes carry data — a 16x224x224x3 float32 batch
is ~9.6 MB; base64-in-JSON would burn ~33% bandwidth + a host copy, and the
hot path here feeds TPU teachers at >1.5k img/s (BASELINE.md). The
reference's equivalent plane is Paddle Serving's bRPC tensor protocol
(distill/distill_worker.py:203-226); the framed-JSON *control* protocol
(coord/wire.py) stays for everything that isn't bulk tensors.

Lives in the DATA layer: the wire moves bytes and is consumed by the
data server, the distill serving plane, and p2p state migration alike —
``data`` must never import ``distill`` (layers.toml), so the shared
framing cannot live on the distill side.  ``edl_tpu.distill.tensor_wire``
remains as an import-compat shim.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any

import numpy as np

from edl_tpu.obs import trace
from edl_tpu.utils import config

MAGIC = b"EDT1"
_HEADER = struct.Struct(">4sI")
MAX_HEADER = 4 * 1024 * 1024
MAX_PAYLOAD = 1024 * 1024 * 1024


class TensorWireError(ConnectionError):
    pass


# Chaos seam, mirroring coord/wire.py: an installed hook may delay,
# drop (raise), hard-close, or garble frames at this boundary — the one
# switch that faults the teacher RPCs, the data server, and p2p state
# migration alike (whose chunk crc32s are exactly what a payload garble
# exercises).
_fault_hook = None


def install_fault_hook(hook):
    """Install (or clear, with None) the tensor-wire fault hook;
    returns the previous hook so a scoped injector can restore it."""
    global _fault_hook
    prev, _fault_hook = _fault_hook, hook
    return prev


def stall_timeout() -> float:
    """Mid-frame stall deadline (EDL_TPU_WIRE_STALL_S, shared with the
    framed-JSON control wire; <=0 disables). Idle connections may block
    per their own timeout policy, but once a frame has started, every
    subsequent recv must produce bytes within this bound — a stalled
    peer becomes a typed TensorWireError, never a wedged server
    thread. The bound is per-recv (progress resets it), so a slow but
    moving bulk transfer is never killed mid-flight."""
    return config.env_float("EDL_TPU_WIRE_STALL_S", 60.0)


def _recv_exact(sock: socket.socket, n: int, *, stall: float = 0.0,
                mid_frame: bool = False) -> bytes:
    buf = bytearray()
    prev = sock.gettimeout()
    bounded = False
    try:
        while len(buf) < n:
            want_bound = stall > 0 and (mid_frame or buf) \
                and (prev is None or prev > stall)
            if want_bound != bounded:
                sock.settimeout(stall if want_bound else prev)
                bounded = want_bound
            try:
                chunk = sock.recv(min(n - len(buf), 1 << 20))
            except TimeoutError as exc:
                if bounded:
                    raise TensorWireError(
                        f"peer stalled mid-frame ({len(buf)}/{n} bytes "
                        f"after {stall:.0f}s)") from exc
                raise
            if not chunk:
                raise TensorWireError("peer closed connection")
            buf.extend(chunk)
    finally:
        if bounded:
            sock.settimeout(prev)
    return bytes(buf)


# sendmsg is limited to IOV_MAX iovecs per call (1024 on Linux); far
# smaller batches already amortize the syscall, and short slices keep the
# per-call bookkeeping cheap.
_IOV_BATCH = 64


def _send_gather(sock: socket.socket, bufs: list) -> None:
    """writev-style gather send: one syscall over many buffers instead of
    one concatenated copy of the whole frame (the old path built
    ``b"".join(payloads)`` — a full extra copy of every tensor on the hot
    serving path)."""
    if not hasattr(sock, "sendmsg"):  # non-POSIX fallback
        for b in bufs:
            sock.sendall(b)
        return
    # nbytes-filter BEFORE the cast: zero-size views (empty tensors) reject
    # cast("B"), and zero-length iovecs are pure overhead anyway.
    views = [memoryview(b).cast("B") for b in bufs
             if memoryview(b).nbytes]
    while views:
        sent = sock.sendmsg(views[:_IOV_BATCH])
        # sendmsg on a blocking socket may still send partially: advance.
        while views and sent >= len(views[0]):
            sent -= len(views[0])
            views.pop(0)
        if views and sent:
            views[0] = views[0][sent:]


def send_tensors(sock: socket.socket, meta: dict[str, Any],
                 tensors: dict[str, np.ndarray] | None = None) -> None:
    tensors = tensors or {}
    # Trace seam, mirroring coord/wire.py: the active span context
    # rides the JSON header's meta under the reserved "_tc" key
    # (copy-on-attach; no-op when tracing is off), so a donor serving
    # chunks joins the restoring pod's resize trace.
    meta = trace.attach(meta)
    descs, payloads = [], []
    for name, arr in tensors.items():
        # numpy-native dtypes only: senders downcast/upcast extension dtypes
        # (e.g. device bf16) to a wire dtype first — teacher logits travel
        # as float32. np.ascontiguousarray promotes 0-d arrays to (1,),
        # so guard it: scalar tensors (state-migration chunks of opt-state
        # counters) must round-trip with their shape intact.
        arr = np.asarray(arr)
        if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        if arr.dtype.str.startswith(("<V", "|V", ">V")):
            raise TensorWireError(
                f"non-wire dtype {arr.dtype} for tensor {name!r}")
        descs.append({"name": name, "dtype": arr.dtype.str,
                      "shape": list(arr.shape)})
        # zero-copy: the array's own buffer rides the gather send
        payloads.append(arr.data)
    header = json.dumps({"meta": meta, "tensors": descs},
                        separators=(",", ":")).encode("utf-8")
    if len(header) > MAX_HEADER:
        raise TensorWireError(f"header too large: {len(header)}")
    hook = _fault_hook
    if hook is not None:
        hook.on_send(sock, _HEADER.size + len(header)
                     + sum(memoryview(p).nbytes for p in payloads))
    _send_gather(sock, [_HEADER.pack(MAGIC, len(header)), header, *payloads])


def recv_tensors(sock: socket.socket
                 ) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
    stall = stall_timeout()
    magic, hlen = _HEADER.unpack(
        _recv_exact(sock, _HEADER.size, stall=stall))
    if magic != MAGIC:
        raise TensorWireError(f"bad magic {magic!r}")
    if hlen > MAX_HEADER:
        raise TensorWireError(f"header too large: {hlen}")
    hook = _fault_hook
    try:
        hbytes = _recv_exact(sock, hlen, stall=stall, mid_frame=True)
        if hook is not None:
            hbytes = hook.on_recv(sock, hbytes, "header")
        header = json.loads(hbytes)
        meta = header["meta"]
        descs = header["tensors"]
    except (ValueError, KeyError, UnicodeDecodeError) as exc:
        raise TensorWireError(f"malformed header: {exc}") from exc
    tensors: dict[str, np.ndarray] = {}
    total = 0
    for d in descs:
        try:
            dtype = np.dtype(d["dtype"])
            shape = tuple(int(x) for x in d["shape"])
            nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        except (TypeError, ValueError, KeyError) as exc:
            raise TensorWireError(f"bad tensor desc {d}: {exc}") from exc
        total += nbytes
        if total > MAX_PAYLOAD:
            raise TensorWireError(f"payload too large: {total}")
        buf = _recv_exact(sock, nbytes, stall=stall, mid_frame=True)
        if hook is not None:
            buf = hook.on_recv(sock, buf, "payload")
        tensors[d["name"]] = np.frombuffer(buf, dtype=dtype).reshape(shape)
    return meta, tensors
