"""Sharded, deterministic, prefetching input pipeline.

Capability of the reference's input stack (reader_cv2 with
`pass_id_as_seed` shuffle, shard-by-trainer-id, DALI double-buffered feed —
example/collective/resnet50/{dali.py,utils/reader_cv2.py}) designed for the
elastic-TPU contract:

- **seed-per-pass determinism**: the epoch's global order is
  `default_rng(seed + epoch)`; an elastic restart replays the identical
  order, so the TrainLoop's step_in_epoch cursor skips exactly the batches
  already consumed (train_with_fleet.py:459-464).
- **shard-by-rank on the GLOBAL order**: rank r of world W takes indices
  `perm[r::W]` — resharding on resize is just a different (r, W), no data
  file re-layout.
- **static shapes**: drop_remainder truncates to a whole number of batches
  per shard so every jit step sees one shape (no XLA recompiles).
- **host-side prefetch**: a daemon thread keeps a bounded queue of
  device-placed batches so H2D transfer overlaps the device step (the DALI
  double-buffering role).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Sequence

import jax
import numpy as np

from edl_tpu.utils.exceptions import EdlDataError


def epoch_indices(n: int, epoch: int, seed: int = 0,
                  shuffle: bool = True) -> np.ndarray:
    """The epoch's deterministic global sample order (seed-per-pass)."""
    if not shuffle:
        return np.arange(n)
    return np.random.default_rng(seed + epoch).permutation(n)


class ArraySource:
    """Indexable source over a dict of equal-length arrays."""

    def __init__(self, arrays: dict[str, np.ndarray]):
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) > 1:
            raise EdlDataError(f"ragged arrays: {lengths}")
        self.arrays = arrays
        self._n = next(iter(lengths.values())) if lengths else 0

    def __len__(self) -> int:
        return self._n

    def batch(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}


def _npz_meta(path: str, first_only: bool = False
              ) -> dict[str, tuple[tuple[int, ...], np.dtype]]:
    """{key: (shape, dtype)} of an .npz shard from the members' .npy
    HEADERS only (NpzFile.__getitem__ would decompress whole members —
    at dataset scale that's a full read of every shard just to size the
    index). `first_only` stops after one member — all a row count needs."""
    import zipfile

    from numpy.lib import format as npy_format

    out: dict[str, tuple[tuple[int, ...], np.dtype]] = {}
    with zipfile.ZipFile(path) as zf:
        names = [n for n in zf.namelist() if n.endswith(".npy")]
        if not names:
            raise EdlDataError(f"{path}: no arrays in npz")
        for name in names[:1] if first_only else names:
            with zf.open(name) as f:
                version = npy_format.read_magic(f)
                try:
                    shape, _, dtype = npy_format._read_array_header(
                        f, version)
                except AttributeError:  # private API moved: full read
                    with np.load(path) as z:
                        arr = z[name[:-4]]
                        shape, dtype = arr.shape, arr.dtype
            out[name[:-4]] = (tuple(shape), np.dtype(dtype))
    return out


def _npz_rows(path: str) -> int:
    """Row count of an .npz shard (header of the first member only)."""
    shape = next(iter(_npz_meta(path, first_only=True).values()))[0]
    if not shape:
        raise EdlDataError(f"{path}: scalar array cannot be a data shard")
    return int(shape[0])


class FileSource:
    """Random-access source over .npz shard files (file-backed ArraySource).

    The file-backed input path of the reference's reader stack (a cv2/
    DALI-class reader walks an image file list, reader_cv2.py) for the
    deterministic loader: an index maps global row -> (file, local row);
    whole shards load lazily on first touch and stay in a small LRU so a
    shuffled epoch doesn't thrash (with shuffle, touches cluster by the
    permutation's locality; size the cache to a few shards).

    Files must share keys; per-file row counts come from reading only the
    first member's .npy header (`_npz_rows`) so constructing the index
    never loads shard data.
    """

    def __init__(self, files: Sequence[str], cache_files: int = 4):
        if not files:
            raise EdlDataError("FileSource needs at least one file")
        if cache_files < 1:
            raise EdlDataError(f"cache_files must be >= 1, got {cache_files}")
        self.files = list(files)
        self._counts = [_npz_rows(f) for f in self.files]
        self._starts = np.cumsum([0] + self._counts)
        self._cache: dict[int, dict[str, np.ndarray]] = {}
        self._cache_order: list[int] = []
        self._meta: dict[str, tuple[tuple[int, ...], np.dtype]] | None = None
        self.cache_files = cache_files
        # DataServer serves one source from a thread per connection; the
        # LRU bookkeeping must not race across concurrent batch() calls.
        self._cache_lock = threading.Lock()

    def __len__(self) -> int:
        return int(self._starts[-1])

    def _shard(self, fi: int) -> dict[str, np.ndarray]:
        with self._cache_lock:
            if fi in self._cache:
                # LRU: refresh recency on hit so the hottest shard survives
                self._cache_order.remove(fi)
                self._cache_order.append(fi)
                return self._cache[fi]
        with np.load(self.files[fi]) as z:  # disk read outside the lock
            arrays = {k: z[k] for k in z.files}
        with self._cache_lock:
            if fi not in self._cache:
                self._cache[fi] = arrays
                self._cache_order.append(fi)
                if len(self._cache_order) > self.cache_files:
                    del self._cache[self._cache_order.pop(0)]
            return self._cache[fi]

    def batch(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        idx = np.asarray(idx)
        if len(idx) == 0:
            # Empty request (e.g. a remote DataServer client asking for
            # zero rows) gets empty arrays of the right shapes/dtypes,
            # not an IndexError from parts[0] below. Header-only scan,
            # parsed once — loading a shard here would churn the LRU
            # for zero rows.
            if self._meta is None:
                self._meta = _npz_meta(self.files[0])
            return {k: np.empty((0,) + shape[1:], dtype)
                    for k, (shape, dtype) in self._meta.items()}
        fis = np.searchsorted(self._starts, idx, side="right") - 1
        locals_ = idx - self._starts[fis]
        out: dict[str, list] = {}
        # group by file so each shard is touched once per batch
        order = np.argsort(fis, kind="stable")
        parts = []
        for fi in np.unique(fis):
            sel = order[fis[order] == fi]
            shard = self._shard(int(fi))
            parts.append((sel, {k: v[locals_[sel]]
                                for k, v in shard.items()}))
        keys = parts[0][1].keys()
        n = len(idx)
        for k in keys:
            first = parts[0][1][k]
            buf = np.empty((n,) + first.shape[1:], first.dtype)
            for sel, arrs in parts:
                buf[sel] = arrs[k]
            out[k] = buf
        return out


class DataLoader:
    """Deterministic sharded batch iterator.

    Args:
      source: ArraySource or anything with __len__ + batch(indices)->dict.
        With `sample_transforms`, the source must instead provide
        `samples(indices) -> list[dict]` (per-sample records of raw,
        possibly variable-size data — e.g. JPEG bytes).
      batch_size: per-RANK batch size.
      rank/world: this trainer's shard of the global order.
      seed: base shuffle seed; epoch is folded in per pass.
      transforms: callables (batch_dict, np.random.Generator) -> batch_dict,
        run on host after collation (augmentation hook); the generator is
        seeded per (epoch, rank) so augmentation replays after a restart.
      sample_transforms: callables (sample_dict, np.random.Generator) ->
        sample_dict run per sample BEFORE collation (the decode/augment
        stage of the reference's xmap reader, reader_cv2.py:94-104) under
        a `decode_threads`-wide pool. Determinism under the pool: every
        sample's RNG seed is drawn from the epoch generator up front, so
        worker scheduling cannot change the stream (unlike the
        reference's `order=False` xmap with shared `random`).
      decode_threads: pool width for sample_transforms (0 = inline). cv2
        releases the GIL in decode/resize, so threads scale on real
        multi-core hosts.
    """

    def __init__(self, source, batch_size: int, *, rank: int = 0,
                 world: int = 1, seed: int = 0, shuffle: bool = True,
                 drop_remainder: bool = True,
                 transforms: Sequence[Callable] = (),
                 sample_transforms: Sequence[Callable] = (),
                 decode_threads: int = 0):
        if world < 1 or not (0 <= rank < world):
            raise EdlDataError(f"bad shard rank={rank} world={world}")
        if sample_transforms and not hasattr(source, "samples"):
            raise EdlDataError(
                "sample_transforms need a source with samples(indices)")
        self.source = source
        self.batch_size = batch_size
        self.rank = rank
        self.world = world
        self.seed = seed
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.transforms = list(transforms)
        self.sample_transforms = list(sample_transforms)
        self.decode_threads = decode_threads
        self._pool = None

    def _decode_pool(self):
        if self._pool is None and self.decode_threads > 0:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.decode_threads,
                thread_name_prefix="data-decode")
        return self._pool

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def _sample_batch(self, idx: np.ndarray,
                      rng: np.random.Generator) -> dict[str, np.ndarray]:
        """samples -> per-sample transforms (pooled) -> collate."""
        samples = self.source.samples(idx)
        # Seeds drawn BEFORE the pool runs: the stream is a pure function
        # of (epoch, rank, position), whatever the thread interleaving.
        seeds = rng.integers(0, 2**63, size=len(samples))

        def work(args):
            sample, seed = args
            srng = np.random.default_rng(seed)
            for t in self.sample_transforms:
                sample = t(sample, srng)
            return sample

        pool = self._decode_pool()
        done = list(pool.map(work, zip(samples, seeds))) if pool \
            else [work(a) for a in zip(samples, seeds)]
        keys = done[0].keys()
        return {k: np.stack([d[k] for d in done]) for k in keys}

    def steps_per_epoch(self) -> int:
        shard = len(self.source) // self.world if self.drop_remainder \
            else -(-len(self.source) // self.world)
        if self.drop_remainder:
            return shard // self.batch_size
        return -(-shard // self.batch_size)

    def epoch(self, epoch: int) -> Iterator[dict[str, np.ndarray]]:
        perm = epoch_indices(len(self.source), epoch, self.seed,
                             self.shuffle)
        mine = perm[self.rank::self.world]
        n_steps = self.steps_per_epoch()
        if n_steps == 0:
            # An empty epoch is always a config bug (batch bigger than the
            # shard); yielding nothing turns it into a silent hang for
            # any epoch-looping consumer.
            raise EdlDataError(
                f"shard of {len(mine)} samples yields 0 batches of "
                f"{self.batch_size} (world={self.world})")
        rng = np.random.default_rng(
            (self.seed + 1) * 1_000_003 + epoch * 4093 + self.rank)
        for i in range(n_steps):
            idx = mine[i * self.batch_size:(i + 1) * self.batch_size]
            if len(idx) == 0:
                break
            if self.sample_transforms:
                batch = self._sample_batch(idx, rng)
            else:
                batch = self.source.batch(idx)
            for t in self.transforms:
                batch = t(batch, rng)
            yield batch

    def __call__(self, epoch: int) -> Iterator[dict[str, np.ndarray]]:
        # TrainLoop's data_fn signature.
        return self.epoch(epoch)


_END = object()


def prefetch(it: Iterable, size: int = 2,
             place: Callable[[Any], Any] | None = None) -> Iterator:
    """Run `it` in a daemon thread, keeping up to `size` items ready.

    Closing the returned generator (or abandoning it — e.g. a stop-resume
    mid-epoch) stops the worker and drains queued items, so device-placed
    batches don't stay pinned in HBM behind a thread blocked on a full
    queue.
    """
    q: queue.Queue = queue.Queue(maxsize=max(1, size))
    err: list[BaseException] = []
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not _put(place(item) if place else item):
                    return
        except BaseException as exc:  # re-raised on the consumer side
            err.append(exc)
        finally:
            _put(_END)

    def gen():
        # Worker starts lazily on first next(): a generator closed (or
        # GC'd) before it ever runs skips the body entirely — including
        # finally — so an eager thread could never be stopped.
        thread = threading.Thread(target=worker, daemon=True,
                                  name="data-prefetch")
        thread.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
            # Drain AND join: the worker may be mid-next(it) on the
            # upstream iterator; returning before it exits would let the
            # caller close that iterator while it is still executing
            # ("generator already executing"). Keep draining while we
            # wait — the worker may still be trying to put one item/_END.
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
                thread.join(timeout=0.1)
                if not thread.is_alive() or time.monotonic() > deadline:
                    break

    return gen()


def prefetch_to_device(it: Iterable, sharding, size: int = 2) -> Iterator:
    """Prefetch + device placement: batches land sharded on the mesh while
    the previous step computes (H2D overlap)."""

    def place(batch):
        return jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), sharding), batch)

    return prefetch(it, size=size, place=place)


# -- host-side image augmentation (reference reader_cv2 capability) --------

def random_flip_lr(batch: dict, rng: np.random.Generator,
                   key: str = "image") -> dict:
    """Per-sample horizontal flip with p=0.5 (NHWC)."""
    imgs = batch[key]
    flip = rng.random(len(imgs)) < 0.5
    out = imgs.copy()
    out[flip] = out[flip, :, ::-1]
    return {**batch, key: out}


def random_crop(batch: dict, rng: np.random.Generator, *, pad: int = 4,
                key: str = "image") -> dict:
    """Pad-and-random-crop (NHWC), the CIFAR/ImageNet-style jitter."""
    imgs = batch[key]
    n, h, w, c = imgs.shape
    padded = np.pad(imgs, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")
    ys = rng.integers(0, 2 * pad + 1, size=n)
    xs = rng.integers(0, 2 * pad + 1, size=n)
    out = np.empty_like(imgs)
    for i in range(n):
        out[i] = padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
    return {**batch, key: out}
