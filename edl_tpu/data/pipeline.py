"""Sharded, deterministic, prefetching input pipeline.

Capability of the reference's input stack (reader_cv2 with
`pass_id_as_seed` shuffle, shard-by-trainer-id, DALI double-buffered feed —
example/collective/resnet50/{dali.py,utils/reader_cv2.py}) designed for the
elastic-TPU contract:

- **seed-per-pass determinism**: the epoch's global order is
  `default_rng(seed + epoch)`; an elastic restart replays the identical
  order, so the TrainLoop's step_in_epoch cursor skips exactly the batches
  already consumed (train_with_fleet.py:459-464).
- **shard-by-rank on the GLOBAL order**: rank r of world W takes indices
  `perm[r::W]` — resharding on resize is just a different (r, W), no data
  file re-layout.
- **static shapes**: drop_remainder truncates to a whole number of batches
  per shard so every jit step sees one shape (no XLA recompiles).
- **host-side prefetch**: a daemon thread keeps a bounded queue of
  device-placed batches so H2D transfer overlaps the device step (the DALI
  double-buffering role).
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Iterable, Iterator, Sequence

import jax
import numpy as np

from edl_tpu.utils.exceptions import EdlDataError


def epoch_indices(n: int, epoch: int, seed: int = 0,
                  shuffle: bool = True) -> np.ndarray:
    """The epoch's deterministic global sample order (seed-per-pass)."""
    if not shuffle:
        return np.arange(n)
    return np.random.default_rng(seed + epoch).permutation(n)


class ArraySource:
    """Indexable source over a dict of equal-length arrays."""

    def __init__(self, arrays: dict[str, np.ndarray]):
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) > 1:
            raise EdlDataError(f"ragged arrays: {lengths}")
        self.arrays = arrays
        self._n = next(iter(lengths.values())) if lengths else 0

    def __len__(self) -> int:
        return self._n

    def batch(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}


def _npz_meta(path: str, first_only: bool = False
              ) -> dict[str, tuple[tuple[int, ...], np.dtype]]:
    """{key: (shape, dtype)} of an .npz shard from the members' .npy
    HEADERS only (NpzFile.__getitem__ would decompress whole members —
    at dataset scale that's a full read of every shard just to size the
    index). `first_only` stops after one member — all a row count needs."""
    import zipfile

    from numpy.lib import format as npy_format

    out: dict[str, tuple[tuple[int, ...], np.dtype]] = {}
    with zipfile.ZipFile(path) as zf:
        names = [n for n in zf.namelist() if n.endswith(".npy")]
        if not names:
            raise EdlDataError(f"{path}: no arrays in npz")
        for name in names[:1] if first_only else names:
            with zf.open(name) as f:
                version = npy_format.read_magic(f)
                try:
                    shape, _, dtype = npy_format._read_array_header(
                        f, version)
                except AttributeError:  # private API moved: full read
                    with np.load(path) as z:
                        arr = z[name[:-4]]
                        shape, dtype = arr.shape, arr.dtype
            out[name[:-4]] = (tuple(shape), np.dtype(dtype))
    return out


def _npz_rows(path: str) -> int:
    """Row count of an .npz shard (header of the first member only)."""
    shape = next(iter(_npz_meta(path, first_only=True).values()))[0]
    if not shape:
        raise EdlDataError(f"{path}: scalar array cannot be a data shard")
    return int(shape[0])


class FileSource:
    """Random-access source over .npz shard files (file-backed ArraySource).

    The file-backed input path of the reference's reader stack (a cv2/
    DALI-class reader walks an image file list, reader_cv2.py) for the
    deterministic loader: an index maps global row -> (file, local row);
    whole shards load lazily on first touch and stay in a small LRU so a
    shuffled epoch doesn't thrash (with shuffle, touches cluster by the
    permutation's locality; size the cache to a few shards).

    Files must share keys; per-file row counts come from reading only the
    first member's .npy header (`_npz_rows`) so constructing the index
    never loads shard data.
    """

    def __init__(self, files: Sequence[str], cache_files: int = 4):
        if not files:
            raise EdlDataError("FileSource needs at least one file")
        if cache_files < 1:
            raise EdlDataError(f"cache_files must be >= 1, got {cache_files}")
        self.files = list(files)
        self._counts = [_npz_rows(f) for f in self.files]
        self._starts = np.cumsum([0] + self._counts)
        # insertion/recency-ordered LRU: hits refresh via O(1)
        # move_to_end (the old list.remove hit path was O(cache) under
        # the lock — measurable with many concurrent DataServer readers)
        self._cache: OrderedDict[int, dict[str, np.ndarray]] = \
            OrderedDict()  # guarded-by: _cache_lock
        self._meta: dict[str, tuple[tuple[int, ...], np.dtype]] | None = None
        self.cache_files = cache_files
        # DataServer serves one source from a thread per connection; the
        # LRU bookkeeping must not race across concurrent batch() calls.
        self._cache_lock = threading.Lock()

    def __len__(self) -> int:
        return int(self._starts[-1])

    def _shard(self, fi: int) -> dict[str, np.ndarray]:
        with self._cache_lock:
            arrays = self._cache.get(fi)
            if arrays is not None:
                self._cache.move_to_end(fi)  # refresh recency on hit
        if arrays is not None:
            return arrays  # slicing happens in batch(), lock released
        with np.load(self.files[fi]) as z:  # disk read outside the lock
            arrays = {k: z[k] for k in z.files}
        with self._cache_lock:
            racer = self._cache.get(fi)
            if racer is not None:  # another thread loaded it first
                self._cache.move_to_end(fi)
                arrays = racer
            else:
                self._cache[fi] = arrays
                while len(self._cache) > self.cache_files:
                    self._cache.popitem(last=False)
        return arrays

    def batch(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        idx = np.asarray(idx)
        if len(idx) == 0:
            # Empty request (e.g. a remote DataServer client asking for
            # zero rows) gets empty arrays of the right shapes/dtypes,
            # not an IndexError from parts[0] below. Header-only scan,
            # parsed once — loading a shard here would churn the LRU
            # for zero rows.
            if self._meta is None:
                self._meta = _npz_meta(self.files[0])
            return {k: np.empty((0,) + shape[1:], dtype)
                    for k, (shape, dtype) in self._meta.items()}
        fis = np.searchsorted(self._starts, idx, side="right") - 1
        locals_ = idx - self._starts[fis]
        if fis[0] == fis[-1] and (fis == fis[0]).all():
            # Whole batch inside ONE shard (always true for single-file
            # sources, common under the permutation's locality): one
            # fancy-index gather per key, in request order — no
            # per-part slicing, no second collation buffer.
            shard = self._shard(int(fis[0]))
            return {k: v[locals_] for k, v in shard.items()}
        out: dict[str, list] = {}
        # group by file so each shard is touched once per batch
        order = np.argsort(fis, kind="stable")
        parts = []
        for fi in np.unique(fis):
            sel = order[fis[order] == fi]
            shard = self._shard(int(fi))
            parts.append((sel, {k: v[locals_[sel]]
                                for k, v in shard.items()}))
        keys = parts[0][1].keys()
        n = len(idx)
        for k in keys:
            first = parts[0][1][k]
            buf = np.empty((n,) + first.shape[1:], first.dtype)
            for sel, arrs in parts:
                buf[sel] = arrs[k]
            out[k] = buf
        return out


def materialize_batch(source, idx: np.ndarray,
                      sample_transforms: Sequence[Callable],
                      transforms: Sequence[Callable],
                      sample_seeds: np.ndarray | None,
                      batch_seed: int | None,
                      pool=None, emit_seed: bool = False
                      ) -> dict[str, np.ndarray]:
    """Compute one batch from a dispatched descriptor.

    THE determinism contract of the loader, shared verbatim by all three
    execution modes (inline, `decode_threads` thread pool, `num_workers`
    process pool — data/mp_loader.py): every random input is an argument
    (`sample_seeds` per sample, `batch_seed` for the post-collation
    transforms), drawn by the parent in step order before dispatch, so
    the batch bytes are a pure function of the descriptor no matter
    where or when it runs.

    `emit_seed` is the device-augmentation hand-off: instead of (or in
    addition to) consuming `batch_seed` on the host, attach it to the
    batch as a 0-d uint32 under ``"augment_seed"`` so the jitted
    on-device augmentation (`ops/augment.py`) folds in the SAME
    parent-drawn draw — still a pure function of the descriptor, so the
    bit-identical-stream contract holds per mode.
    """
    if sample_transforms:
        samples = source.samples(idx)

        def work(args):
            sample, seed = args
            srng = np.random.default_rng(seed)
            for t in sample_transforms:
                sample = t(sample, srng)
            return sample

        done = list(pool.map(work, zip(samples, sample_seeds))) if pool \
            else [work(a) for a in zip(samples, sample_seeds)]
        keys = done[0].keys()
        batch = {k: np.stack([d[k] for d in done]) for k in keys}
    else:
        batch = source.batch(idx)
    if transforms:
        brng = np.random.default_rng(batch_seed)
        for t in transforms:
            batch = t(batch, brng)
    if emit_seed:
        batch = {**batch,
                 "augment_seed": np.asarray(batch_seed & 0xFFFFFFFF,
                                            dtype=np.uint32)}
    return batch


def _close_mp_pool(pool) -> None:
    # weakref.finalize target: must not reference the DataLoader
    pool.close()


class DataLoader:
    """Deterministic sharded batch iterator.

    Args:
      source: ArraySource or anything with __len__ + batch(indices)->dict.
        With `sample_transforms`, the source must instead provide
        `samples(indices) -> list[dict]` (per-sample records of raw,
        possibly variable-size data — e.g. JPEG bytes).
      batch_size: per-RANK batch size.
      rank/world: this trainer's shard of the global order.
      seed: base shuffle seed; epoch is folded in per pass.
      transforms: callables (batch_dict, np.random.Generator) -> batch_dict,
        run on host after collation (augmentation hook); the generator is
        seeded per (epoch, rank, step) so augmentation replays after a
        restart.
      sample_transforms: callables (sample_dict, np.random.Generator) ->
        sample_dict run per sample BEFORE collation (the decode/augment
        stage of the reference's xmap reader, reader_cv2.py:94-104) under
        a `decode_threads`-wide pool. Determinism under the pool: every
        sample's RNG seed is drawn from the epoch generator up front, so
        worker scheduling cannot change the stream (unlike the
        reference's `order=False` xmap with shared `random`).
      decode_threads: THREAD pool width for sample_transforms (0 =
        inline). cv2 releases the GIL in decode/resize, so threads scale
        on real multi-core hosts — until Python-side transform code
        (numpy slicing, collation) serializes on the GIL.
      num_workers: PROCESS pool width (0 = the inline/thread path above,
        unchanged default; None = the `EDL_TPU_LOADER_WORKERS` env
        contract). With workers, batches are computed in forked worker
        processes and handed back through a shared-memory slot ring with
        zero-copy reassembly in strict step order (data/mp_loader.py) —
        the path that scales past the GIL. Bit-identical to the inline
        stream; `decode_threads` is ignored (each worker decodes its own
        whole batch). Yielded batches are views over the ring, valid
        until the following `next()` — `device_put`/copy before
        advancing if a batch must outlive that (prefetch_to_device
        already does).

    A DataLoader is a context manager; `close()` joins the decode pool
    and the worker processes and unlinks every shm segment. TrainLoop
    closes the loader it drives; abandoning the object entirely still
    tears the pool down via GC.

    `emit_batch_seed=True` is the DEVICE-augmentation feed
    (ops/augment.py): the per-step batch seed — the same parent-drawn
    draw host `transforms` consume — rides each batch as a 0-d uint32
    under ``"augment_seed"``; `prefetch_to_device(augment=...)` /
    `TrainLoop(augment_fn=...)` pop it before placement and hand it to
    the jitted augment, so crop/flip/normalize overlap the step instead
    of burning host cores.  Works in every execution mode (the seed is
    part of the descriptor's pure function).
    """

    def __init__(self, source, batch_size: int, *, rank: int = 0,
                 world: int = 1, seed: int = 0, shuffle: bool = True,
                 drop_remainder: bool = True,
                 transforms: Sequence[Callable] = (),
                 sample_transforms: Sequence[Callable] = (),
                 decode_threads: int = 0,
                 num_workers: int | None = None,
                 emit_batch_seed: bool = False):
        if world < 1 or not (0 <= rank < world):
            raise EdlDataError(f"bad shard rank={rank} world={world}")
        if sample_transforms and not hasattr(source, "samples"):
            raise EdlDataError(
                "sample_transforms need a source with samples(indices)")
        if num_workers is None:
            from edl_tpu.data.mp_loader import default_num_workers
            num_workers = default_num_workers()
        if num_workers < 0:
            raise EdlDataError(f"num_workers must be >= 0, got {num_workers}")
        self.source = source
        self.batch_size = batch_size
        self.rank = rank
        self.world = world
        self.seed = seed
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.transforms = list(transforms)
        self.sample_transforms = list(sample_transforms)
        self.decode_threads = decode_threads
        self.num_workers = num_workers
        self.emit_batch_seed = emit_batch_seed
        self._pool = None
        self._mp_pool = None
        self._mp_finalizer = None

    def _decode_pool(self):
        if self._pool is None and self.decode_threads > 0:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.decode_threads,
                thread_name_prefix="data-decode")
        return self._pool

    def _ensure_mp_pool(self, probe_batch: dict[str, np.ndarray]):
        """The worker pool, (re)built lazily and reused across epochs.

        `probe_batch` (the first batch, computed in-parent) sizes the
        shm slots; a later batch that somehow outgrows its slot falls
        back to the queue, it does not fail.
        """
        if self._mp_pool is not None and not (self._mp_pool.closed
                                              or self._mp_pool.broken):
            return self._mp_pool
        from edl_tpu.data import mp_loader
        pool = mp_loader.MpLoaderPool(
            self.source, self.sample_transforms, self.transforms,
            self.num_workers, mp_loader.probe_slot_bytes(probe_batch),
            emit_seed=self.emit_batch_seed)
        self._mp_pool = pool
        # GC of an abandoned DataLoader (or interpreter exit) must still
        # join workers and unlink the shm ring.
        self._mp_finalizer = weakref.finalize(self, _close_mp_pool, pool)
        return pool

    def close(self) -> None:
        """Join the decode pool / worker processes, unlink shm (idempotent;
        the loader remains usable — pools rebuild lazily on next use)."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._mp_pool is not None:
            self._mp_pool.close()
            if self._mp_finalizer is not None:
                self._mp_finalizer.detach()
                self._mp_finalizer = None
            self._mp_pool = None

    def __enter__(self) -> "DataLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def steps_per_epoch(self) -> int:
        shard = len(self.source) // self.world if self.drop_remainder \
            else -(-len(self.source) // self.world)
        if self.drop_remainder:
            return shard // self.batch_size
        return -(-shard // self.batch_size)

    def _epoch_descriptors(self, epoch: int, start_step: int):
        """(step, indices, sample_seeds, batch_seed) for steps >=
        start_step — with every seed draw made in step order from the
        per-(epoch, rank) generator, INCLUDING the skipped steps', so a
        mid-epoch resume replays the identical remainder."""
        perm = epoch_indices(len(self.source), epoch, self.seed,
                             self.shuffle)
        mine = perm[self.rank::self.world]
        n_steps = self.steps_per_epoch()
        if n_steps == 0:
            # An empty epoch is always a config bug (batch bigger than the
            # shard); yielding nothing turns it into a silent hang for
            # any epoch-looping consumer.
            raise EdlDataError(
                f"shard of {len(mine)} samples yields 0 batches of "
                f"{self.batch_size} (world={self.world})")
        rng = np.random.default_rng(
            (self.seed + 1) * 1_000_003 + epoch * 4093 + self.rank)
        descs = []
        for i in range(n_steps):
            idx = mine[i * self.batch_size:(i + 1) * self.batch_size]
            if len(idx) == 0:
                break
            sseeds = rng.integers(0, 2**63, size=len(idx)) \
                if self.sample_transforms else None
            bseed = int(rng.integers(0, 2**63)) \
                if self.transforms or self.emit_batch_seed else None
            if i >= start_step:
                descs.append((i, idx, sseeds, bseed))
        return descs

    def epoch(self, epoch: int, start_step: int = 0
              ) -> Iterator[dict[str, np.ndarray]]:
        """The epoch's batch stream from the `start_step` cursor
        (seed-per-pass: the same (epoch, start_step) always replays the
        same remainder — the elastic stop-resume contract)."""
        descs = self._epoch_descriptors(epoch, start_step)
        if self.num_workers > 0:
            yield from self._epoch_mp(descs)
            return
        pool = self._decode_pool()
        for _step, idx, sseeds, bseed in descs:
            yield materialize_batch(self.source, idx,
                                    self.sample_transforms,
                                    self.transforms, sseeds, bseed, pool,
                                    emit_seed=self.emit_batch_seed)

    def _epoch_mp(self, descs) -> Iterator[dict[str, np.ndarray]]:
        if not descs:
            return
        if self._mp_pool is None or self._mp_pool.closed \
                or self._mp_pool.broken:
            # First mp epoch: compute batch 0 in-parent (bit-identical —
            # same descriptor, same materialize_batch) to size the ring,
            # then fork the workers and hand them the rest.
            step0, idx0, sseeds0, bseed0 = descs[0]
            probe = materialize_batch(self.source, idx0,
                                      self.sample_transforms,
                                      self.transforms, sseeds0, bseed0,
                                      emit_seed=self.emit_batch_seed)
            yield probe
            pool = self._ensure_mp_pool(probe)
            descs = descs[1:]
        else:
            pool = self._mp_pool
        yield from pool.imap(descs)

    def __call__(self, epoch: int) -> Iterator[dict[str, np.ndarray]]:
        # TrainLoop's data_fn signature.
        return self.epoch(epoch)


_END = object()


def prefetch(it: Iterable, size: int = 2,
             place: Callable[[Any], Any] | None = None) -> Iterator:
    """Run `it` in a daemon thread, keeping up to `size` items ready.

    Closing the returned generator (or abandoning it — e.g. a stop-resume
    mid-epoch) stops the worker and drains queued items, so device-placed
    batches don't stay pinned in HBM behind a thread blocked on a full
    queue.
    """
    q: queue.Queue = queue.Queue(maxsize=max(1, size))
    err: list[BaseException] = []
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not _put(place(item) if place else item):
                    return
        except BaseException as exc:  # re-raised on the consumer side
            err.append(exc)
        finally:
            _put(_END)

    def gen():
        # Worker starts lazily on first next(): a generator closed (or
        # GC'd) before it ever runs skips the body entirely — including
        # finally — so an eager thread could never be stopped.
        thread = threading.Thread(target=worker, daemon=True,
                                  name="data-prefetch")
        thread.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
            # Drain AND join: the worker may be mid-next(it) on the
            # upstream iterator; returning before it exits would let the
            # caller close that iterator while it is still executing
            # ("generator already executing"). Keep draining while we
            # wait — the worker may still be trying to put one item/_END.
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
                thread.join(timeout=0.1)
                if not thread.is_alive() or time.monotonic() > deadline:
                    break

    return gen()


def place_array(x, sharding):
    """`jax.device_put` with the ring-view aliasing guard.

    Borrowed views (OWNDATA=False — e.g. the mp loader's shm-ring
    batches) are copied before placement: `jax.device_put` zero-copies
    suitably aligned host buffers on the CPU backend (the placed Array
    ALIASES the numpy memory) and DMAs asynchronously on TPU, so placing
    a ring view directly would hand the step memory that a worker
    process rewrites as soon as the slot recycles.  Arrays that OWN
    their memory (inline-mode batches, `PackedSource` gathers) place
    without the defensive copy — nobody else holds that buffer."""
    x = np.asarray(x)
    if not x.flags["OWNDATA"]:
        x = np.array(x)
    return jax.device_put(x, sharding)


def pop_augment_seed(batch, augment) -> tuple:
    """Split a loader batch into (payload, seed) for device augmentation.

    The 0-d ``"augment_seed"`` must come OFF the batch before placement
    (a scalar cannot shard over the mesh's batch axes) and is consumed
    only by `augment`; a seed with no augment configured — or the
    reverse — is a wiring bug surfaced here instead of as a cryptic
    sharding error or a silently never-augmented run."""
    from edl_tpu.ops.augment import AUGMENT_SEED_KEY
    has_seed = isinstance(batch, dict) and AUGMENT_SEED_KEY in batch
    if augment is None:
        if has_seed:
            raise EdlDataError(
                "loader emitted augment_seed but no device augment fn is "
                "configured (pass augment= / TrainLoop(augment_fn=...), "
                "or drop DataLoader(emit_batch_seed=True))")
        return batch, None
    if not has_seed:
        raise EdlDataError(
            "device augment configured but the batch carries no "
            "augment_seed — construct the DataLoader with "
            "emit_batch_seed=True")
    batch = dict(batch)
    return batch, batch.pop(AUGMENT_SEED_KEY)


def prefetch_to_device(it: Iterable, sharding, size: int = 2,
                       augment: Callable | None = None) -> Iterator:
    """Prefetch + device placement: batches land sharded on the mesh while
    the previous step computes (H2D overlap).  See `place_array` for the
    borrowed-view copy rule.

    `augment` is the device-side augmentation hook (a jitted
    `(batch, seed) -> batch` from `ops.augment.make_device_augment`):
    the parent-drawn per-step seed is popped off the batch
    (`DataLoader(emit_batch_seed=True)`), the raw bytes are placed, and
    the augment dispatches asynchronously — crop/flip/normalize run on
    the accelerator UNDER the previous step, costing the host nothing."""

    def place(batch):
        batch, seed = pop_augment_seed(batch, augment)
        placed = jax.tree.map(lambda x: place_array(x, sharding), batch)
        if augment is not None:
            placed = augment(placed, seed)
        return placed

    return prefetch(it, size=size, place=place)


# -- host-side image augmentation (reference reader_cv2 capability) --------

def random_flip_lr(batch: dict, rng: np.random.Generator,
                   key: str = "image") -> dict:
    """Per-sample horizontal flip with p=0.5 (NHWC)."""
    imgs = batch[key]
    flip = rng.random(len(imgs)) < 0.5
    out = imgs.copy()
    out[flip] = out[flip, :, ::-1]
    return {**batch, key: out}


def random_crop(batch: dict, rng: np.random.Generator, *, pad: int = 4,
                key: str = "image") -> dict:
    """Pad-and-random-crop (NHWC), the CIFAR/ImageNet-style jitter.

    Vectorized: one sliding-window VIEW over the padded tensor (no
    window materialization) + a single fancy-index gather picks every
    image's (y, x) window at once — the per-image Python loop this
    replaces was ~40% of the npz input plane's host time at 224px.
    Bit-identical to the loop: the (ys, xs) draws and selected windows
    are unchanged.
    """
    imgs = batch[key]
    n, h, w, c = imgs.shape
    padded = np.pad(imgs, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")
    ys = rng.integers(0, 2 * pad + 1, size=n)
    xs = rng.integers(0, 2 * pad + 1, size=n)
    # windows: (n, 2p+1, 2p+1, c, h, w) strided view
    windows = np.lib.stride_tricks.sliding_window_view(
        padded, (h, w), axis=(1, 2))
    out = np.ascontiguousarray(
        windows[np.arange(n), ys, xs].transpose(0, 2, 3, 1))
    return {**batch, key: out}
