"""Elastic task dispenser: file-shard leasing over the coordination store.

Capability of the reference's Go master task service (pkg/master/
service.go:17-66,95-208 — GetTask/TaskFinished/TaskErrored/NewEpoch with a
Todo/Pending/Done/Failed state machine and timeout->requeue, over a
file-list dataset, pkg/master/file_list_dataset.go:5-39), re-designed for
this stack: instead of a dedicated master daemon owning in-memory queues,
the task state machine lives in the coordination store as one record per
task, and every transition is a compare-and-swap — so any pod can dispense
or consume, a dead consumer's leases expire by wall-clock deadline and the
task is re-claimed by a CAS race (exactly the rank-claim pattern,
collective/register.py), and task state survives coordinator restarts
whenever the store is the durable `edl-store` daemon.

States (value is the task's JSON record; the CAS expect-string is the
exact bytes last read, so two claimers can never both win):

    todo --get_task--> pending(owner, deadline)
    pending --finished--> done
    pending --errored--> todo       (failures+1; failed when > max)
    pending[expired] --get_task--> pending(new owner, failures+1)

Record-level data checkpointing falls out: `done` tasks are never
re-served, so an elastic restart resumes the epoch from the store's task
table instead of re-reading data (reference collective/dataloader.py:
100-120 "PROCSSED" record skip).
"""

from __future__ import annotations

import json
import random
import time
from dataclasses import dataclass
from typing import Any, Sequence

from edl_tpu.coord.store import Store
from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.data.task_master")


class EdlTaskError(EdlError):
    pass


@dataclass
class Task:
    """A leased work unit (one file shard / record range)."""

    task_id: int
    epoch: int
    spec: dict
    failures: int
    _key: str = ""
    _raw: str = ""  # exact stored JSON at claim time (CAS expect)


def _task_record(spec: dict, state: str, owner: str = "",
                 deadline: float = 0.0, failures: int = 0) -> str:
    return json.dumps({"spec": spec, "state": state, "owner": owner,
                       "deadline": deadline, "failures": failures},
                      sort_keys=True)


def file_list_specs(files: Sequence[str],
                    records_per_task: int | None = None,
                    counts: Sequence[int] | None = None) -> list[dict]:
    """Task specs from a file list (reference file_list_dataset.go:5-39).

    Without counts: one task per file. With per-file record counts and
    records_per_task, files split into record-range tasks
    {"file", "start", "stop"}.
    """
    if counts is None or records_per_task is None:
        return [{"file": f} for f in files]
    specs = []
    for f, n in zip(files, counts):
        for lo in range(0, n, records_per_task):
            specs.append({"file": f, "start": lo,
                          "stop": min(lo + records_per_task, n)})
    return specs


class TaskMaster:
    """Dispense/lease/complete tasks for one job over the store.

    Args:
      store: coordination store (client or in-mem).
      job_id: namespace.
      owner: this consumer's id (pod id).
      lease_timeout: seconds before an unfinished pending task is
        re-claimable (reference task timeout, cmd/master/master.go:36).
      max_failures: errored/timed-out attempts before a task is failed
        (reference task-timeout-max=3).
    """

    def __init__(self, store: Store, job_id: str, owner: str, *,
                 lease_timeout: float = 60.0, max_failures: int = 3,
                 clock=time.time):
        self.store = store
        self.job_id = job_id
        self.owner = owner
        self.lease_timeout = lease_timeout
        self.max_failures = max_failures
        self._clock = clock
        # Claim-candidate cache: the leftover todo keys of the last full
        # scan. A claim is then one get + one CAS (amortized) instead of
        # a whole-prefix scan + parse per claim — the difference between
        # file-shard granularity (hundreds of tasks) and record-range
        # granularity (10^5+, file_list_specs with records_per_task).
        # Staleness is harmless: every claim re-reads the record and the
        # CAS guards the transition.
        self._cache_epoch: int | None = None
        self._todo_keys: list[str] = []

    # -- keys ---------------------------------------------------------------

    def _epoch_key(self) -> str:
        return f"/{self.job_id}/data/epoch"

    def _task_prefix(self, epoch: int) -> str:
        return f"/{self.job_id}/data/e{epoch}/task/"

    def _task_key(self, epoch: int, task_id: int) -> str:
        return f"{self._task_prefix(epoch)}{task_id:06d}"

    # -- epoch lifecycle ----------------------------------------------------

    def current_epoch(self) -> int | None:
        rec = self.store.get(self._epoch_key())
        return None if rec is None else json.loads(rec.value)["epoch"]

    def init_epoch(self, epoch: int, specs: Sequence[dict]) -> bool:
        """Install the epoch's task table (idempotent; the AddDataSet +
        NewEpoch analogue, service.go:175-188). Returns True if this call
        installed it, False if it already existed."""
        header = json.dumps({"epoch": epoch, "n_tasks": len(specs)})
        cur = self.store.get(self._epoch_key())
        if cur is not None:
            cur_epoch = json.loads(cur.value)["epoch"]
            if cur_epoch >= epoch:
                return False
            if not self.store.compare_and_swap(self._epoch_key(), cur.value,
                                               header):
                return False
        elif not self.store.put_if_absent(self._epoch_key(), header):
            return False
        for i, spec in enumerate(specs):
            self.store.put_if_absent(self._task_key(epoch, i),
                                     _task_record(spec, "todo"))
        log.info("epoch %d installed: %d tasks", epoch, len(specs))
        return True

    # -- dispensing ---------------------------------------------------------

    def _claim(self, rec, epoch: int, failures: int,
               data: dict | None = None) -> Task | None:
        if data is None:
            data = json.loads(rec.value)
        new_raw = _task_record(data["spec"], "pending", self.owner,
                               self._clock() + self.lease_timeout, failures)
        if self.store.compare_and_swap(rec.key, rec.value, new_raw):
            task_id = int(rec.key.rsplit("/", 1)[1])
            return Task(task_id, epoch, data["spec"], failures,
                        _key=rec.key, _raw=new_raw)
        return None

    def get_task(self) -> Task | None:
        """Claim a todo task, or re-claim an expired pending one.

        None means nothing claimable right now: poll again unless
        `epoch_done()`. A timed-out re-claim counts as a failure against
        the task (service.go:134-150); tasks over max_failures are marked
        failed and never re-dispensed.
        """
        epoch = self.current_epoch()
        if epoch is None:
            raise EdlTaskError("no epoch installed")
        if self._cache_epoch != epoch:
            self._cache_epoch, self._todo_keys = epoch, []
        # Fast path: drain cached candidates (re-read + CAS per try).
        # Bounded misses: a mostly-stale cache (another consumer drained
        # the epoch while we stalled) must not turn into O(n) sequential
        # round-trips — after a run of misses, drop it and bulk-rescan.
        misses = 0
        while self._todo_keys and misses < 16:
            rec = self.store.get(self._todo_keys.pop())
            if rec is None:
                misses += 1
                continue
            data = json.loads(rec.value)
            if data["state"] != "todo":
                misses += 1
                continue
            task = self._claim(rec, epoch, data["failures"], data)
            if task is not None:
                return task
            misses += 1
        self._todo_keys = []
        # Cache dry: full scan (also the only place expired pendings and
        # epoch completion are observed — bounded-staleness by design).
        recs, _ = self.store.get_prefix(self._task_prefix(epoch))
        now = self._clock()
        todo, expired = [], []
        for rec in recs:
            data = json.loads(rec.value)
            if data["state"] == "todo":
                todo.append((rec, data))
            elif data["state"] == "pending" and data["deadline"] <= now:
                expired.append((rec, data))
        # Contending consumers spread over the claimable set instead of
        # all CAS-racing the first record.
        random.shuffle(todo)
        for i, (rec, data) in enumerate(todo):
            task = self._claim(rec, epoch, data["failures"])
            if task is not None:
                self._todo_keys = [r.key for r, _ in todo[i + 1:]]
                return task
        for rec, data in expired:
            failures = data["failures"] + 1
            if failures > self.max_failures:
                failed = _task_record(data["spec"], "failed",
                                      failures=failures)
                if self.store.compare_and_swap(rec.key, rec.value, failed):
                    log.warning("task %s failed after %d timeouts",
                                rec.key, failures)
                continue
            task = self._claim(rec, epoch, failures)
            if task is not None:
                log.info("re-claimed expired task %s (owner was %r)",
                         rec.key, data["owner"])
                return task
        return None

    # -- consumer transitions -----------------------------------------------

    def heartbeat(self, task: Task) -> bool:
        """Extend the lease mid-task; False = ownership lost (stop work)."""
        new_raw = _task_record(task.spec, "pending", self.owner,
                               self._clock() + self.lease_timeout,
                               task.failures)
        if self.store.compare_and_swap(task._key, task._raw, new_raw):
            task._raw = new_raw
            return True
        return False

    def finished(self, task: Task) -> bool:
        """pending(us) -> done. False = we lost the lease and another
        consumer owns (or finished) it — the caller must NOT count this
        task's records as its own contribution (exactly-once accounting)."""
        done = _task_record(task.spec, "done", self.owner,
                            failures=task.failures)
        ok = self.store.compare_and_swap(task._key, task._raw, done)
        if not ok:
            log.warning("finished(%s): ownership lost", task._key)
        return ok

    def errored(self, task: Task, reason: str = "") -> None:
        """pending(us) -> todo (or failed past max_failures)."""
        failures = task.failures + 1
        state = "failed" if failures > self.max_failures else "todo"
        new_raw = _task_record(task.spec, state, failures=failures)
        if self.store.compare_and_swap(task._key, task._raw, new_raw):
            log.warning("task %s errored (%s) -> %s", task._key, reason,
                        state)

    # -- progress -----------------------------------------------------------

    def counts(self, epoch: int | None = None) -> dict[str, int]:
        if epoch is None:
            epoch = self.current_epoch()
            if epoch is None:
                raise EdlTaskError("no epoch installed")
        out = {"todo": 0, "pending": 0, "done": 0, "failed": 0}
        recs, _ = self.store.get_prefix(self._task_prefix(epoch))
        for rec in recs:
            out[json.loads(rec.value)["state"]] += 1
        return out

    def epoch_done(self, epoch: int | None = None) -> bool:
        """True when nothing is left to dispense or wait for."""
        c = self.counts(epoch)
        return c["todo"] == 0 and c["pending"] == 0
