"""edl-lint — the invariant-enforcing analysis plane.

The reference EDL rotted into unimportable skeleton code: ``NameError``s
at import time, undefined classes, contracts that lived only in design
docs (SURVEY.md, "working vs. skeleton code").  This package is the
countermeasure: every contract the docs state is encoded as a machine
check and wired as a CI gate, so drift fails the build instead of
accumulating.

Two planes:

- **edl-lint** (``python -m edl_tpu.analysis lint``) — a stdlib-only AST
  framework (`core.py`) with five checkers (`checks/`):

  * ``layering``            — the declared layer map (`layers.toml`):
    coord/scaler/analysis never import jax/numpy/train, data never
    imports distill; violations name the full import chain.
  * ``env-registry``        — every ``EDL_TPU_*`` env read goes through
    the central declaration table in `utils/config.py` AND has a row in
    the ``doc/usage.md`` reference table (flags undocumented knobs and
    dead doc rows both ways).
  * ``guarded-by``          — fields annotated ``# guarded-by: _lock``
    are only mutated under ``with self._lock``.
  * ``resource-lifecycle``  — classes that create threads / shared
    memory / sockets define a teardown method, and instantiation sites
    are context-managed, finally-closed, or registered long-lived.
  * ``sim-determinism``     — wall clocks and unseeded RNGs are banned
    from the scaler simulator and everything it imports (the
    seeded-exact bench contract, made structural).

  Inline suppressions: ``# edl-lint: disable=<check>(<reason>)`` — the
  reason is mandatory, unused suppressions are themselves findings.

- **lockgraph** (`lockgraph.py`) — a ``threading`` instrumentation
  harness + pytest plugin (``EDL_TPU_LOCKGRAPH=1``) that records
  per-thread lock-acquisition orderings during the test run, builds the
  global lock-order graph, and fails on cycles (potential ABBA
  deadlock) with both acquisition stacks printed.

This package is pure stdlib — importable (and runnable in CI) without
jax, numpy, or the accelerator stack; ``tests/test_analysis.py`` pins
that.
"""

from edl_tpu.analysis.core import Finding, LintResult, Project, run_lint

__all__ = ["Finding", "LintResult", "Project", "run_lint"]
