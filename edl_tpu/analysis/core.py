"""edl-lint core: file model, suppressions, import graph, runner.

Stdlib-only (the ``layering`` checker pins the whole package jax/numpy
free — a lint that needed the accelerator stack could not gate a
scheduler-node build).  Python 3.10 has no ``tomllib``, so the layer
map is read by :func:`load_toml_lite`, a parser for the small TOML
subset ``layers.toml`` actually uses (tables, string/number/bool
scalars, single-line string arrays).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field


# --------------------------------------------------------------------------
# toml-lite


def load_toml_lite(text: str) -> dict:
    """Parse the TOML subset used by ``layers.toml``: ``[a.b]`` tables,
    ``key = "str" | 123 | 1.5 | true | ["a", "b"]`` pairs, ``#`` comments.
    Raises ``ValueError`` on anything it does not understand — a silently
    half-read layer map would be a lint that lies."""
    root: dict = {}
    table = root
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            if not line.endswith("]"):
                raise ValueError(f"layers.toml:{lineno}: malformed table {line!r}")
            table = root
            for part in line[1:-1].strip().split("."):
                table = table.setdefault(part.strip(), {})
            continue
        if "=" not in line:
            raise ValueError(f"layers.toml:{lineno}: expected key = value, got {line!r}")
        key, _, value = line.partition("=")
        table[key.strip()] = _toml_value(value.strip(), lineno)
    return root


def _toml_value(value: str, lineno: int):
    if value.startswith("["):
        if not value.endswith("]"):
            raise ValueError(f"layers.toml:{lineno}: arrays must be single-line")
        body = value[1:-1].strip()
        if not body:
            return []
        return [_toml_value(item.strip(), lineno)
                for item in _split_toml_array(body, lineno)]
    if value.startswith('"'):
        if not value.endswith('"') or len(value) < 2:
            raise ValueError(f"layers.toml:{lineno}: unterminated string {value!r}")
        return value[1:-1]
    if value in ("true", "false"):
        return value == "true"
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        raise ValueError(f"layers.toml:{lineno}: unsupported value {value!r}") from None


def _split_toml_array(body: str, lineno: int) -> list[str]:
    items, cur, in_str = [], [], False
    for ch in body:
        if ch == '"':
            in_str = not in_str
        if ch == "," and not in_str:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if in_str:
        raise ValueError(f"layers.toml:{lineno}: unterminated string in array")
    if cur:
        items.append("".join(cur))
    return [i.strip() for i in items if i.strip()]


# --------------------------------------------------------------------------
# findings + suppressions

# the directive grammar: 'disable=' then comma-joined check(reason)
# items (see doc/design_analysis.md; the literal text is not written
# out here because this comment would itself match the regex)
_SUPPRESS_RE = re.compile(r"#\s*edl-lint:\s*disable=(.+)$")
_SUPPRESS_ITEM_RE = re.compile(r"\s*([a-z0-9-]+)\(([^()]+)\)\s*$")
# '# guarded-by: _lock'   (field annotation — see checks/guarded_by.py)
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
# '# holds-lock: _lock'   (method called only with the lock already held)
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_]\w*)")
# '# lifecycle: long-lived(reason)' (registered long-lived singleton site)
_LONG_LIVED_RE = re.compile(r"#\s*lifecycle:\s*long-lived\(([^()]+)\)")


@dataclass(frozen=True)
class Finding:
    check: str
    path: str          # repo-relative, '/'-separated
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


@dataclass(frozen=True)
class Suppression:
    check: str
    reason: str
    path: str
    line: int


class LintError(ValueError):
    """Malformed lint directive (e.g. a suppression without a reason)."""


def _parse_suppressions(path: str, line: int, comment: str) -> list[Suppression]:
    m = _SUPPRESS_RE.search(comment)
    if not m:
        return []
    out = []
    # split on commas OUTSIDE parens: reasons may contain commas
    depth, cur, items = 0, [], []
    for ch in m.group(1):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    items.append("".join(cur))
    for item in items:
        im = _SUPPRESS_ITEM_RE.match(item)
        if not im:
            raise LintError(
                f"{path}:{line}: malformed suppression {item.strip()!r} — "
                "the syntax is '# edl-lint: disable=<check>(<reason>)' and "
                "the reason is mandatory")
        out.append(Suppression(im.group(1), im.group(2).strip(), path, line))
    return out


# --------------------------------------------------------------------------
# source files


class SourceFile:
    """One parsed module: AST + per-line comments, suppressions and
    lint annotations (extracted with ``tokenize`` so ``#`` inside string
    literals can't fake a directive)."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.comments: dict[int, str] = {}
        self.suppressions: dict[int, list[Suppression]] = {}
        self.guarded_by: dict[int, str] = {}      # line -> lock name
        self.holds_lock: dict[int, str] = {}      # line -> lock name
        self.long_lived: dict[int, str] = {}      # line -> reason
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            self.comments[line] = tok.string
            sups = _parse_suppressions(path, line, tok.string)
            if sups:
                self.suppressions.setdefault(line, []).extend(sups)
            for regex, store in ((_GUARDED_RE, self.guarded_by),
                                 (_HOLDS_RE, self.holds_lock),
                                 (_LONG_LIVED_RE, self.long_lived)):
                m = regex.search(tok.string)
                if m:
                    store[line] = m.group(1).strip()
        # parent links: checkers walk from a node up to its enclosing
        # with/function/class without re-deriving scope per check
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc
        return None

    def enclosing_class(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None


# --------------------------------------------------------------------------
# project model


def _is_type_checking_guard(node: ast.AST) -> bool:
    if not isinstance(node, ast.If):
        return False
    test = node.test
    return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or \
        (isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING")


@dataclass(frozen=True)
class ImportEdge:
    module: str        # absolute dotted module name as imported
    line: int
    top_level: bool    # module-body import (executes at import time)


class Project:
    """The lint subject: every ``.py`` under the configured paths, plus
    the import graph (import-time edges only — a function-scoped import
    is a deliberate deferral and does not violate import-time layering)."""

    def __init__(self, root: str, config: dict):
        self.root = os.path.abspath(root)
        self.config = config
        self.files: dict[str, SourceFile] = {}
        self.errors: list[Finding] = []
        paths = (config.get("lint") or {}).get("paths") or ["edl_tpu"]
        for rel in paths:
            self._collect(os.path.join(self.root, rel))
        # module name -> repo-relative path, for import-graph resolution
        self.modules: dict[str, str] = {}
        for path in self.files:
            name = path[:-3].replace("/", ".")
            if name.endswith(".__init__"):
                name = name[: -len(".__init__")]
            self.modules[name] = path
        self.imports: dict[str, list[ImportEdge]] = {
            path: self._imports_of(sf) for path, sf in self.files.items()}

    @classmethod
    def load(cls, root: str) -> "Project":
        cfg_path = os.path.join(root, "edl_tpu", "analysis", "layers.toml")
        with open(cfg_path, encoding="utf-8") as f:
            config = load_toml_lite(f.read())
        return cls(root, config)

    def _collect(self, base: str) -> None:
        if os.path.isfile(base):
            self._add(base)
            return
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for name in sorted(filenames):
                if name.endswith(".py"):
                    self._add(os.path.join(dirpath, name))

    def _add(self, abspath: str) -> None:
        rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        try:
            self.files[rel] = SourceFile(rel, text)
        except SyntaxError as exc:
            self.errors.append(Finding(
                "parse", rel, exc.lineno or 0, f"syntax error: {exc.msg}"))
        except LintError as exc:
            self.errors.append(Finding("suppression", rel, 0, str(exc)))

    # -- import graph -------------------------------------------------------

    def _imports_of(self, sf: SourceFile) -> list[ImportEdge]:
        pkg_parts = sf.path[:-3].split("/")
        if pkg_parts[-1] == "__init__":
            pkg_parts = pkg_parts[:-1]
        edges: list[ImportEdge] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                names = [(alias.name, None) for alias in node.names]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    prefix = ".".join(base + ([node.module] if node.module
                                              else []))
                else:
                    prefix = node.module or ""
                names = [(prefix, alias.name) for alias in node.names]
            else:
                continue
            top = self._is_import_time(sf, node)
            for module, attr in names:
                edges.append(ImportEdge(module, node.lineno, top))
                # 'from pkg import sub' may bind a submodule: record the
                # joined name too when it resolves to a project module
                if attr and f"{module}.{attr}" in getattr(self, "modules", {}):
                    edges.append(ImportEdge(f"{module}.{attr}", node.lineno,
                                            top))
        return edges

    def _is_import_time(self, sf: SourceFile, node: ast.AST) -> bool:
        """Module-body import (incl. inside try/if) but not inside a
        function and not under ``if TYPE_CHECKING:``."""
        for anc in sf.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            if _is_type_checking_guard(anc):
                return False
        return True

    def import_time_deps(self, path: str) -> list[tuple[str, ImportEdge]]:
        """(resolved target, edge) for every import-time edge of `path`.
        Importing ``a.b.c`` also executes ``a`` and ``a.b`` — ancestor
        package ``__init__``s are included as implicit targets, because
        a jax import hiding in a package ``__init__`` breaks the layer
        contract exactly as hard as a direct one."""
        out = []
        for edge in self.imports.get(path, ()):
            if not edge.top_level:
                continue
            parts = edge.module.split(".")
            for i in range(1, len(parts) + 1):
                prefix = ".".join(parts[:i])
                target = self.modules.get(prefix)
                if target is not None and target != path:
                    out.append((target, edge))
            if edge.module.split(".")[0] not in ("edl_tpu",):
                out.append((edge.module, edge))   # external dep, unresolved
        return out


# --------------------------------------------------------------------------
# runner


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[tuple[Finding, Suppression]] = field(default_factory=list)
    suppressions: list[Suppression] = field(default_factory=list)
    checks_run: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "checks": self.checks_run,
            "findings": [vars(f) for f in self.findings],
            "suppressed": [{**vars(f), "reason": s.reason}
                           for f, s in self.suppressed],
            "suppressions": [vars(s) for s in self.suppressions],
        }


def run_lint(root: str, checks: list[str] | None = None) -> LintResult:
    """Run every registered checker over the project at `root`.

    A finding is suppressed iff its line carries a matching
    ``# edl-lint: disable=<check>(<reason>)``; suppressions that match
    no finding are reported as ``unused-suppression`` findings so the
    inventory can never rot."""
    from edl_tpu.analysis.checks import CHECKS
    project = Project.load(root)
    result = LintResult()
    result.findings.extend(project.errors)
    selected = {name: fn for name, fn in CHECKS.items()
                if checks is None or name in checks}
    result.checks_run = sorted(selected)
    raw: list[Finding] = []
    seen: set[Finding] = set()
    for name in sorted(selected):
        for f in selected[name](project):
            # one finding per (check, site, message): a forbidden module
            # reachable over several import paths is one defect
            if f not in seen:
                seen.add(f)
                raw.append(f)

    for sf in project.files.values():
        for sups in sf.suppressions.values():
            result.suppressions.extend(sups)
    used: set[tuple[str, int, str]] = set()
    for f in raw:
        sups = project.files.get(f.path)
        match = None
        if sups is not None:
            for s in sups.suppressions.get(f.line, []):
                if s.check == f.check:
                    match = s
                    break
        if match is not None:
            result.suppressed.append((f, match))
            used.add((match.path, match.line, match.check))
        else:
            result.findings.append(f)
    for s in result.suppressions:
        if (s.path, s.line, s.check) not in used:
            result.findings.append(Finding(
                "unused-suppression", s.path, s.line,
                f"suppression for '{s.check}' matches no finding — "
                "delete it (reason was: " + s.reason + ")"))
    result.findings.sort(key=lambda f: (f.path, f.line, f.check))
    return result
