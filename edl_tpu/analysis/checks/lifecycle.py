"""resource-lifecycle — resource-owning classes are closeable and closed.

Pass 1 finds **resource classes**: a class that constructs a thread,
process, socket, shared-memory segment, or subprocess (the
``resource_calls`` list in layers.toml) and KEEPS it — the constructor
result is stored on ``self`` (directly, through a conditional
expression, through a ``X(...).start()`` builder chain, appended into a
``self`` container, or via a local later assigned to ``self``).  Such a
class must define a teardown method (``close``/``stop``/``shutdown``/
``cancel``) — a kept thread you cannot join is a leak by construction.
A resource that stays local to one method (started and joined in
``handle()``, say) is that method's business, not the class contract's.

Pass 2 audits every **instantiation site** of a resource class across
the project.  A site passes when ownership is visibly bounded:

- the call is the context expression of a ``with`` (directly or inside
  ``contextlib.closing(...)`` / ``enter_context``);
- the result is returned / yielded / produced by a ``lambda`` (a
  factory: the caller owns it);
- the result lands on ``self`` in a class that itself has a teardown
  method (ownership transfer: the audit moves to the owner's sites);
- the result is bound to a local that the same function either tears
  down in a ``finally:``, stores onto a closeable ``self``, or hands to
  the constructor of a project class with a teardown method (ownership
  handoff — e.g. a registrar wrapped into a pool handle);
- the line carries ``# lifecycle: long-lived(<reason>)`` — the explicit
  registry of process-lifetime singletons, reason mandatory.

Everything else — a local that leaks on the exception path, a bare
expression statement, a module-level instance without the annotation —
is a finding.  Resolution is name-based across the project (no type
inference), which is exactly as blunt as it sounds and in practice
right for this codebase's flat naming.
"""

from __future__ import annotations

import ast

from edl_tpu.analysis.core import Finding, Project, SourceFile

_TEARDOWN_CALLS = {"close", "stop", "shutdown", "terminate", "kill",
                   "cancel"}
_CONTAINER_ADDS = {"append", "add", "appendleft", "insert"}


def _cfg(project: Project) -> tuple[set[str], set[str]]:
    spec = project.config.get("lifecycle") or {}
    calls = set(spec.get("resource_calls") or
                ["Thread", "Process", "SharedMemory", "socket",
                 "create_connection", "create_server", "Popen"])
    teardown = set(spec.get("teardown_methods") or
                   ["close", "stop", "shutdown", "cancel"])
    return calls, teardown


def _call_name(node: ast.Call) -> str | None:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self")


def _class_methods(cls: ast.ClassDef) -> set[str]:
    return {n.name for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _has_teardown(cls: ast.ClassDef, teardown: set[str],
                  all_classes: dict[str, ast.ClassDef]) -> bool:
    seen: set[str] = set()
    stack = [cls]
    while stack:
        cur = stack.pop()
        if cur.name in seen:
            continue
        seen.add(cur.name)
        if _class_methods(cur) & teardown:
            return True
        for base in cur.bases:
            bname = base.id if isinstance(base, ast.Name) else (
                base.attr if isinstance(base, ast.Attribute) else None)
            if bname and bname in all_classes:
                stack.append(all_classes[bname])
    return False


def _binding(sf: SourceFile, call: ast.Call):
    """How the fresh instance is bound, unwrapping pass-through shapes.

    Returns one of: ("with",) ("factory",) ("self",) ("local", name,
    node) ("container-self",) (None, node) — node being the outermost
    expression the value flowed into (for context-specific rules)."""
    node: ast.AST = call
    parent = sf.parents.get(node)
    while True:
        # value-preserving expression wrappers
        if isinstance(parent, (ast.IfExp, ast.BoolOp, ast.NamedExpr)):
            node, parent = parent, sf.parents.get(parent)
            continue
        # contextlib.closing(X(...)) / stack.enter_context(X(...))
        if isinstance(parent, ast.Call) and _call_name(parent) in (
                "closing", "enter_context"):
            node, parent = parent, sf.parents.get(parent)
            continue
        # builder chain: X(...).start() returns the instance
        if isinstance(parent, ast.Attribute):
            gp = sf.parents.get(parent)
            if isinstance(gp, ast.Call) and gp.func is parent:
                node, parent = gp, sf.parents.get(gp)
                continue
        break
    if isinstance(parent, ast.withitem):
        return ("with",)
    if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom,
                           ast.Lambda)):
        return ("factory",)
    if isinstance(parent, ast.Assign):
        for t in parent.targets:
            if _is_self_attr(t):
                return ("self",)
        for t in parent.targets:
            if isinstance(t, ast.Name):
                return ("local", t.id, parent)
    # self._things.append(X(...))
    if isinstance(parent, ast.Call) and isinstance(parent.func,
                                                   ast.Attribute) \
            and parent.func.attr in _CONTAINER_ADDS \
            and _is_self_attr(parent.func.value):
        return ("container-self",)
    return (None, node)


def _local_stored_on_self(sf: SourceFile, func: ast.AST, var: str) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == var \
                and any(_is_self_attr(t) for t in node.targets):
            return True
    return False


def _local_handed_to_owner(sf: SourceFile, func: ast.AST, var: str,
                           teardown: set[str],
                           all_classes: dict[str, ast.ClassDef]) -> bool:
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        cname = _call_name(node)
        owner = all_classes.get(cname) if cname else None
        if owner is None or not _has_teardown(owner, teardown, all_classes):
            continue
        if any(isinstance(a, ast.Name) and a.id == var for a in node.args):
            return True
    return False


def _finally_closes(sf: SourceFile, func: ast.AST, var: str) -> bool:
    for node in ast.walk(func):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for fin in node.finalbody:
            for sub in ast.walk(fin):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _TEARDOWN_CALLS \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == var:
                    return True
    return False


def _find_resource_classes(project: Project, resource_calls: set[str]
                           ) -> dict[str, tuple[str, ast.ClassDef]]:
    """{class name: (path, node)} for classes that construct AND KEEP a
    raw resource (see module docstring for what 'keep' means)."""
    out: dict[str, tuple[str, ast.ClassDef]] = {}
    for path, sf in sorted(project.files.items()):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef) or node.name in out:
                continue
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Call)
                        and _call_name(sub) in resource_calls):
                    continue
                kept = False
                bind = _binding(sf, sub)
                if bind[0] in ("self", "container-self"):
                    kept = True
                elif bind[0] == "local":
                    func = sf.enclosing_function(sub)
                    kept = func is not None and _local_stored_on_self(
                        sf, func, bind[1])
                if kept and sf.enclosing_class(sub) is node:
                    out[node.name] = (path, node)
                    break
    return out


def check_lifecycle(project: Project):
    resource_calls, teardown = _cfg(project)
    classes = _find_resource_classes(project, resource_calls)

    all_classes: dict[str, ast.ClassDef] = {}
    for sf in project.files.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                all_classes.setdefault(node.name, node)

    # Pass 1: every keeping resource class defines a teardown method.
    for name, (path, node) in sorted(classes.items()):
        if _has_teardown(node, teardown, all_classes):
            continue
        yield Finding(
            "resource-lifecycle", path, node.lineno,
            f"class '{name}' keeps threads/sockets/shared memory on "
            f"self but defines no teardown method "
            f"({'/'.join(sorted(teardown))})")

    # Pass 2: instantiation sites of resource classes.
    for path, sf in sorted(project.files.items()):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = _call_name(node)
            if cname not in classes:
                continue
            encl = sf.enclosing_class(node)
            if encl is not None and encl.name == cname:
                continue  # a class's own methods may self-construct
            if _site_ok(sf, node, teardown, all_classes):
                continue
            yield Finding(
                "resource-lifecycle", sf.path, node.lineno,
                f"'{cname}' instantiated without bounded ownership — "
                "use a context manager, close it in a finally:, store "
                "it on a closeable owner, or register the site with "
                "'# lifecycle: long-lived(<reason>)'")


def _site_ok(sf: SourceFile, call: ast.Call, teardown: set[str],
             all_classes: dict[str, ast.ClassDef]) -> bool:
    # the annotation may sit at the end of the call line or on its own
    # line directly above (long reasons don't fit after the call)
    if sf.long_lived.get(call.lineno) is not None \
            or sf.long_lived.get(call.lineno - 1) is not None:
        return True
    bind = _binding(sf, call)
    if bind[0] in ("with", "factory"):
        return True
    if bind[0] in ("self", "container-self"):
        encl = sf.enclosing_class(call)
        return encl is not None and _has_teardown(encl, teardown,
                                                  all_classes)
    if bind[0] == "local":
        var = bind[1]
        func = sf.enclosing_function(call)
        if func is None:
            return False  # module-level: annotate or restructure
        if _finally_closes(sf, func, var):
            return True
        if _local_stored_on_self(sf, func, var):
            encl = sf.enclosing_class(call)
            return encl is not None and _has_teardown(encl, teardown,
                                                      all_classes)
        if _local_handed_to_owner(sf, func, var, teardown, all_classes):
            return True
    return False
