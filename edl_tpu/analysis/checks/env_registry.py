"""env-registry — source <-> declaration <-> documentation knob parity.

The reference shipped ~70 ``PADDLE_*`` knobs parsed ad-hoc across
entrypoints with a doc page that covered a fraction of them; this repo
was drifting the same way (~70 ``EDL_TPU_*`` reads vs ~46 documented).
This checker makes the drift impossible:

1. every ``os.environ[...]`` / ``os.environ.get`` / ``os.getenv`` /
   ``in os.environ`` READ of an ``EDL_TPU_*`` name must live in
   ``utils/config.py`` — everything else goes through the typed helpers
   (``env_str``/``env_int``/``env_float``/``env_flag``/``env_present``)
   or a ``field(env=...)`` declaration;
2. every referenced name must be declared in the central ``ENV_VARS``
   table in ``utils/config.py``;
3. every declared name must have a row in the ``doc/usage.md``
   reference table (``| `EDL_TPU_X` | ... |``) — and every doc row must
   be a declared name (dead rows flagged);
4. a declared name nothing reads any more is a dead declaration.

Environment WRITES (``os.environ["EDL_TPU_X"] = ...``, ``setdefault``,
``pop``) are launcher/demo business and allowed anywhere — but the name
written must still be declared, so a knob cannot exist only as a write.
"""

from __future__ import annotations

import ast
import os
import re

from edl_tpu.analysis.core import Finding, Project

_READ_METHODS = {"get", "__getitem__"}
_WRITE_METHODS = {"setdefault", "pop"}
_HELPERS = {"env_str", "env_int", "env_float", "env_flag", "env_present"}


def _env_cfg(project: Project) -> dict:
    return project.config.get("env") or {}


def _name_re(prefix: str) -> re.Pattern:
    return re.compile(re.escape(prefix) + r"[A-Z0-9_]+\Z")


def _is_os_environ(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os")


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def parse_env_vars_table(project: Project, config_path: str) -> dict[str, int]:
    """``ENV_VARS`` dict literal in utils/config.py -> {name: line}."""
    sf = project.files.get(config_path)
    if sf is None:
        return {}
    for node in ast.walk(sf.tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == "ENV_VARS"):
            continue
        if not isinstance(value, ast.Dict):
            return {}
        out = {}
        for key in value.keys:
            name = _const_str(key)
            if name is not None:
                out[name] = key.lineno
        return out
    return {}


def parse_doc_rows(root: str, doc_rel: str, prefix: str) -> dict[str, int]:
    """Markdown table rows ``| `EDL_TPU_X` | ... |`` -> {name: line}."""
    path = os.path.join(root, doc_rel)
    row_re = re.compile(r"^\|\s*`(" + re.escape(prefix) + r"[A-Z0-9_]+)`")
    rows: dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                m = row_re.match(line)
                if m:
                    rows.setdefault(m.group(1), lineno)
    except OSError:
        pass
    return rows


def _collect_refs(project: Project, prefix: str):
    """Yield (path, line, name, kind) for every EDL_TPU_* reference.

    kind: 'raw-read' | 'raw-write' | 'helper' | 'field' | 'mention'
    """
    name_re = _name_re(prefix)
    for path, sf in sorted(project.files.items()):
        for node in ast.walk(sf.tree):
            # os.environ[NAME] — read unless it is an assignment target
            if isinstance(node, ast.Subscript) and _is_os_environ(node.value):
                name = _const_str(node.slice)
                if name and name_re.match(name):
                    store = isinstance(node.ctx, (ast.Store, ast.Del))
                    yield (path, node.lineno, name,
                           "raw-write" if store else "raw-read")
            # os.environ.get/ setdefault/ pop, os.getenv
            elif isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and _is_os_environ(func.value) \
                        and func.attr in (_READ_METHODS | _WRITE_METHODS):
                    name = _const_str(node.args[0]) if node.args else None
                    if name and name_re.match(name):
                        kind = ("raw-read" if func.attr in _READ_METHODS
                                else "raw-write")
                        yield (path, node.lineno, name, kind)
                elif isinstance(func, ast.Attribute) \
                        and func.attr == "getenv" \
                        and isinstance(func.value, ast.Name) \
                        and func.value.id == "os":
                    name = _const_str(node.args[0]) if node.args else None
                    if name and name_re.match(name):
                        yield (path, node.lineno, name, "raw-read")
                elif (isinstance(func, ast.Name) and func.id in _HELPERS) \
                        or (isinstance(func, ast.Attribute)
                            and func.attr in _HELPERS):
                    name = _const_str(node.args[0]) if node.args else None
                    if name and name_re.match(name):
                        yield (path, node.lineno, name, "helper")
                elif (isinstance(func, ast.Name) and func.id == "field") \
                        or (isinstance(func, ast.Attribute)
                            and func.attr == "field"):
                    for kw in node.keywords:
                        if kw.arg != "env":
                            continue
                        vals = [kw.value] if not isinstance(
                            kw.value, ast.Tuple) else list(kw.value.elts)
                        for v in vals:
                            name = _const_str(v)
                            if name and name_re.match(name):
                                yield (path, v.lineno, name, "field")
            # 'NAME in os.environ' membership read
            elif isinstance(node, ast.Compare) \
                    and len(node.comparators) == 1 \
                    and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                    and _is_os_environ(node.comparators[0]):
                name = _const_str(node.left)
                if name and name_re.match(name):
                    yield (path, node.lineno, name, "raw-read")
            # bare full-name string constants (env-forward lists etc.):
            # a mention must be declared, but does not count as a read
            elif isinstance(node, ast.Constant):
                name = _const_str(node)
                if name and name_re.match(name):
                    yield (path, node.lineno, name, "mention")


def check_env_registry(project: Project):
    cfg = _env_cfg(project)
    prefix = cfg.get("prefix", "EDL_TPU_")
    config_path = cfg.get("config_module", "edl_tpu/utils/config.py")
    doc_rel = cfg.get("doc", "doc/usage.md")

    declared = parse_env_vars_table(project, config_path)
    if not declared and config_path in project.files:
        yield Finding("env-registry", config_path, 1,
                      "central ENV_VARS declaration table not found "
                      "(expected a dict literal named ENV_VARS)")
        return
    doc_rows = parse_doc_rows(project.root, doc_rel, prefix)

    reads: set[str] = set()
    referenced: set[str] = set()
    seen_undeclared: set[tuple[str, int, str]] = set()
    for path, line, name, kind in _collect_refs(project, prefix):
        referenced.add(name)
        if kind in ("raw-read", "helper", "field"):
            reads.add(name)
        if kind == "raw-read" and path != config_path:
            yield Finding(
                "env-registry", path, line,
                f"direct environment read of '{name}' — go through "
                "utils/config (env_str/env_int/env_float/env_flag/"
                "env_present or field(env=...))")
        if name not in declared and (path, line, name) not in seen_undeclared:
            seen_undeclared.add((path, line, name))
            yield Finding(
                "env-registry", path, line,
                f"'{name}' is not declared in the ENV_VARS table in "
                "utils/config.py")

    for name, line in sorted(declared.items()):
        if name not in doc_rows:
            yield Finding(
                "env-registry", config_path, line,
                f"declared knob '{name}' has no row in the {doc_rel} "
                "env reference table")
        if name not in reads:
            yield Finding(
                "env-registry", config_path, line,
                f"declared knob '{name}' is never read anywhere — "
                "dead declaration (delete it and its doc row)")

    doc_path = doc_rel.replace(os.sep, "/")
    for name, line in sorted(doc_rows.items()):
        if name not in declared:
            yield Finding(
                "env-registry", doc_path, line,
                f"doc row for '{name}' matches no declared knob — "
                "dead doc row")
