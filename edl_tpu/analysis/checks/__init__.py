"""Checker registry: name -> callable(Project) -> Iterable[Finding].

Checker names are what suppressions reference
(``# edl-lint: disable=layering(...)``), so they are part of the lint's
public contract — rename one and every suppression for it goes stale
(and the ``unused-suppression`` check will say so).
"""

from edl_tpu.analysis.checks.layering import check_layering
from edl_tpu.analysis.checks.env_registry import check_env_registry
from edl_tpu.analysis.checks.guarded_by import check_guarded_by
from edl_tpu.analysis.checks.lifecycle import check_lifecycle
from edl_tpu.analysis.checks.determinism import check_determinism

CHECKS = {
    "layering": check_layering,
    "env-registry": check_env_registry,
    "guarded-by": check_guarded_by,
    "resource-lifecycle": check_lifecycle,
    "sim-determinism": check_determinism,
}

__all__ = ["CHECKS"]
