"""sim-determinism — wall clocks and unseeded RNGs banned from the sim.

The scaler bench contract is seeded-exact: the same seed replays the
same decision trace bit-for-bit (doc/design_scaler.md), which is what
makes policy tournaments and CI convergence gates meaningful.  Until
now one stray ``time.time()`` in a policy helper would break that
silently.  This check makes the contract structural over the files
named in ``[determinism] files`` (layers.toml) **plus every project
module they import, transitively** (function-scoped imports included —
a deferred import is still executed by the sim).

Banned:

- ``time.time/time_ns/monotonic/monotonic_ns/perf_counter[_ns]`` —
  the sim runs on a virtual clock that ticks in whole decisions;
- ``datetime.now/utcnow/today`` and ``date.today``;
- module-level ``random.<fn>()`` (the global RNG — including
  ``random.seed``: seeding global state is how two sims contaminate
  each other); ``random.Random(seed)`` with an argument is the blessed
  form, argless ``random.Random()`` falls back to OS entropy and is
  banned;
- ``np.random.<fn>()`` except ``default_rng/RandomState/Generator/
  SeedSequence`` called WITH a seed argument (the scaler layer is
  numpy-free anyway — the rule exists so the checker generalizes to
  any files listed in layers.toml).
"""

from __future__ import annotations

import ast

from edl_tpu.analysis.core import Finding, Project

_TIME_BANNED = {"time", "time_ns", "monotonic", "monotonic_ns",
                "perf_counter", "perf_counter_ns"}
_DATETIME_BANNED = {"now", "utcnow", "today"}
_NP_RANDOM_SEEDED_OK = {"default_rng", "RandomState", "Generator",
                        "SeedSequence"}
_RANDOM_CLASSES = {"Random"}


def _scope_files(project: Project) -> set[str]:
    spec = project.config.get("determinism") or {}
    roots = [f.replace("\\", "/") for f in (spec.get("files") or [])]
    scope: set[str] = set()
    queue = [f for f in roots if f in project.files]
    while queue:
        path = queue.pop()
        if path in scope:
            continue
        scope.add(path)
        for edge in project.imports.get(path, ()):
            if not edge.top_level:
                continue   # a deferred import runs code the sim never calls
            # exact module only — executing an ancestor package __init__
            # merely DEFINES modules; the sim does not call into them
            target = project.modules.get(edge.module)
            if target and target not in scope:
                queue.append(target)
    return scope


def check_determinism(project: Project):
    for path in sorted(_scope_files(project)):
        sf = project.files[path]
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = _banned_call(node)
            if msg:
                yield Finding(
                    "sim-determinism", path, node.lineno,
                    msg + " — the sim contract is seeded-exact "
                    "(virtual clock + explicit seeded RNGs only)")


def _banned_call(node: ast.Call) -> str | None:
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    owner = func.value
    # time.<fn>
    if isinstance(owner, ast.Name) and owner.id == "time" \
            and func.attr in _TIME_BANNED:
        return f"wall-clock call time.{func.attr}()"
    # datetime.now / datetime.datetime.now / date.today
    if func.attr in _DATETIME_BANNED:
        names = _dotted(owner)
        if names and names[0] in ("datetime", "date"):
            return f"wall-clock call {'.'.join(names)}.{func.attr}()"
    # random.<fn> on the MODULE (global RNG); random.Random(seed) is ok
    if isinstance(owner, ast.Name) and owner.id == "random":
        if func.attr in _RANDOM_CLASSES:
            if not node.args and not node.keywords:
                return "argless random.Random() (OS-entropy seed)"
            return None
        if func.attr in ("SystemRandom",):
            return "random.SystemRandom() (OS entropy)"
        return f"global-RNG call random.{func.attr}()"
    # np.random.<fn> / numpy.random.<fn>
    names = _dotted(owner)
    if len(names) == 2 and names[0] in ("np", "numpy") \
            and names[1] == "random":
        if func.attr in _NP_RANDOM_SEEDED_OK:
            if node.args or node.keywords:
                return None
            return f"unseeded np.random.{func.attr}()"
        return f"global-RNG call np.random.{func.attr}()"
    return None


def _dotted(node: ast.AST) -> list[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []
