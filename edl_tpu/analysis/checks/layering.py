"""layering — the declared layer map, enforced on import-time edges.

For every file in a declared layer, walk the project-internal
import-time graph (BFS) and flag any path that reaches a forbidden
module.  The finding names the whole chain — ``coord/collector.py ->
edl_tpu.utils.timeline -> jax`` — because the violation is almost never
in the file you have open; it is two hops down in a helper that grew a
convenience import.

Import-time means module-body edges only (including under ``try:`` — a
guarded ``import jax`` still executes jax when it is installed, which
is exactly when the layer contract matters).  Function-scoped imports
and ``if TYPE_CHECKING:`` blocks are deliberate deferrals and exempt.
Importing ``a.b.c`` also executes ``a/__init__`` and ``a.b/__init__``,
so ancestor packages are implicit edges (core.Project handles this).
"""

from __future__ import annotations

from edl_tpu.analysis.core import Finding, Project


def _forbidden_match(module: str, forbidden: list[str]) -> str | None:
    for ban in forbidden:
        if module == ban or module.startswith(ban + "."):
            return ban
    return None


def _module_name(project: Project, target: str) -> str:
    """Dotted module name of a dep target (project path or external)."""
    if target in project.files:
        name = target[:-3].replace("/", ".")
        if name.endswith(".__init__"):
            name = name[: -len(".__init__")]
        return name
    return target


def check_layering(project: Project):
    layers = project.config.get("layers") or {}
    for layer_name, spec in sorted(layers.items()):
        packages = spec.get("packages") or []
        forbidden = spec.get("forbidden") or []
        members = [path for path in project.files
                   if any(path == p or path.startswith(p + "/")
                          for p in packages)]
        for path in sorted(members):
            yield from _check_file(project, layer_name, path, forbidden)


def _check_file(project: Project, layer: str, path: str,
                forbidden: list[str]):
    seen: set[str] = {path}
    queue: list[str] = [path]
    via: dict[str, tuple[str, object]] = {}   # node -> (parent, edge)
    while queue:
        cur = queue.pop(0)
        for target, edge in project.import_time_deps(cur):
            ban = _forbidden_match(_module_name(project, target), forbidden)
            if ban is not None:
                yield Finding(
                    "layering", path, _root_line(via, path, cur, edge),
                    f"layer '{layer}' must not import '{ban}' "
                    f"(chain: {_chain(via, path, cur, edge, target)})")
            elif target in project.files and target not in seen:
                seen.add(target)
                via[target] = (cur, edge)
                queue.append(target)


def _root_line(via: dict, root: str, cur: str, edge) -> int:
    """The ROOT file's import line that starts the chain (that is the
    line the suppression must sit on, and the line a fix edits)."""
    if cur == root:
        return edge.line
    node = cur
    while via[node][0] != root:
        node = via[node][0]
    return via[node][1].line


def _chain(via: dict, root: str, cur: str, edge, target: str) -> str:
    hops = [f"{target} (line {edge.line} of {cur})"]
    node = cur
    while node != root:
        parent, pedge = via[node]
        hops.append(f"{node} (line {pedge.line} of {parent})")
        node = parent
    hops.append(root)
    return " <- ".join(hops)
