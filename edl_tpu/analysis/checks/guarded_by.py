"""guarded-by — annotated shared state is only mutated under its lock.

Annotation: put ``# guarded-by: _lock`` on the line where the field is
first assigned (conventionally in ``__init__``)::

    self._pending = 0          # guarded-by: _lock

From then on, every mutation of ``self._pending`` anywhere in that
class — assignment, augmented assignment, ``del``, subscript store, or
a call of a known mutating method (``append``/``pop``/``update``/...)
— must sit lexically inside ``with self._lock:`` (``Condition`` objects
count: ``with self._cond:`` takes the underlying lock).

Exemptions, each an explicit happens-before argument:

- ``__init__`` — construction precedes any concurrent access;
- methods whose ``def`` line carries ``# holds-lock: _lock`` — the
  documented contract that every caller already holds the lock;
- the annotation line itself.

The check is lexical and per-class: a ``with`` in an OUTER function
does not bless a mutation inside a nested ``def`` (the closure may run
on another thread after the lock is dropped — that is precisely the bug
class this exists for).  Reads are not checked; the annotation grammar
deliberately stays small enough to trust.
"""

from __future__ import annotations

import ast

from edl_tpu.analysis.core import Finding, Project, SourceFile

_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse",
}


def check_guarded_by(project: Project):
    for path, sf in sorted(project.files.items()):
        if not sf.guarded_by:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                yield from _check_class(sf, node)


def _annotated_fields(sf: SourceFile, cls: ast.ClassDef) -> dict[str, str]:
    """{field name: lock name} from guarded-by comments inside `cls`
    whose line holds a ``self.<field> = ...`` (or ``: type = ...``)."""
    fields: dict[str, str] = {}
    for node in ast.walk(cls):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            name = _self_attr(t)
            if name is None:
                continue
            lock = sf.guarded_by.get(t.lineno)
            if lock is not None:
                fields[name] = lock
    return fields


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _check_class(sf: SourceFile, cls: ast.ClassDef):
    fields = _annotated_fields(sf, cls)
    if not fields:
        return
    for node in ast.walk(cls):
        for name, mutation_kind in _mutations(node):
            lock = fields.get(name)
            if lock is None:
                continue
            if _is_protected(sf, node, cls, lock):
                continue
            yield Finding(
                "guarded-by", sf.path, node.lineno,
                f"'self.{name}' is guarded by 'self.{lock}' but this "
                f"{mutation_kind} is outside 'with self.{lock}' "
                "(and not in __init__ or a '# holds-lock' method)")


def _mutations(node: ast.AST):
    """(field, kind) for mutations rooted at this single node."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield from _target_mutation(t, "assignment")
    elif isinstance(node, ast.AugAssign):
        yield from _target_mutation(node.target, "augmented assignment")
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            yield from _target_mutation(t, "del")
    elif isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            name = _self_attr(func.value)
            if name is not None:
                yield name, f".{func.attr}() call"


def _target_mutation(target: ast.expr, kind: str):
    name = _self_attr(target)
    if name is not None:
        yield name, kind
        return
    # self.field[...] = / del self.field[...]
    if isinstance(target, ast.Subscript):
        name = _self_attr(target.value)
        if name is not None:
            yield name, f"subscript {kind}"
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _target_mutation(elt, kind)


def _is_protected(sf: SourceFile, node: ast.AST, cls: ast.ClassDef,
                  lock: str) -> bool:
    func = None
    for anc in sf.ancestors(node):
        if isinstance(anc, ast.With):
            if func is None and any(
                    _self_attr(item.context_expr) == lock
                    for item in anc.items):
                return True
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if func is None:
                func = anc
                if anc.name == "__init__":
                    return True
                if sf.holds_lock.get(anc.lineno) == lock:
                    return True
            # keep walking: a method nested in a method never happens
            # here, but the enclosing CLASS decides when to stop
        elif isinstance(anc, ast.Lambda) and func is None:
            func = anc
        elif anc is cls:
            break
    return False
