"""CLI: ``python -m edl_tpu.analysis lint`` (the CI gate) and
``python -m edl_tpu.analysis lockgraph-selftest`` (proves the race
detector catches its seeded hazards).

``lint`` exits 1 on any unsuppressed finding; ``--json PATH`` writes
the machine-readable result (findings + the full suppression inventory
with reasons) that ``tools/lint_report.py`` turns into the audit
markdown.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _cmd_lint(args) -> int:
    from edl_tpu.analysis.core import run_lint
    checks = args.check or None
    result = run_lint(args.root, checks=checks)
    for f in result.findings:
        print(f.render())
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(result.to_dict(), fh, indent=2, sort_keys=True)
    n_sup = len(result.suppressed)
    if result.findings:
        print(f"edl-lint: {len(result.findings)} finding(s) "
              f"({n_sup} suppressed) across checks: "
              f"{', '.join(result.checks_run)}", file=sys.stderr)
        return 1
    print(f"edl-lint: clean ({', '.join(result.checks_run)}; "
          f"{n_sup} suppression(s) in force)")
    return 0


def _cmd_lockgraph_selftest(args) -> int:
    del args
    from edl_tpu.analysis.lockgraph import selftest
    return selftest(verbose=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.analysis",
        description="edl-lint: invariant checkers + lock-order analysis")
    sub = parser.add_subparsers(dest="cmd", required=True)

    lint = sub.add_parser("lint", help="run the AST checkers (CI gate)")
    lint.add_argument("--root", default=os.getcwd(),
                      help="repo root (default: cwd)")
    lint.add_argument("--check", action="append",
                      help="run only this checker (repeatable)")
    lint.add_argument("--json", default=None,
                      help="write the machine-readable result here")
    lint.set_defaults(fn=_cmd_lint)

    lg = sub.add_parser("lockgraph-selftest",
                        help="prove the lock-order detector catches the "
                             "seeded ABBA pair and the queue hazard")
    lg.set_defaults(fn=_cmd_lockgraph_selftest)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
