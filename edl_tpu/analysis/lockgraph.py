"""lockgraph — a dynamic lock-order race detector for the control plane.

Ten subsystems of this codebase run hand-rolled threads (async ckpt
writer, mp loader, watch streams, teacher batcher, drain actuator, ...)
whose lock discipline was, until this module, checked by nothing but
review.  TSAN covers only ``native/store``.  lockgraph closes the gap
for the Python planes the way lockdep does for the kernel: **record the
order in which every thread nests lock acquisitions, build the global
lock-order graph, and fail on cycles** — a cycle is a potential ABBA
deadlock even if this particular run interleaved safely.

How it instruments (``install()``):

- ``threading.Lock`` / ``threading.RLock`` factories are replaced; a
  lock **created from edl code** (creation site resolved by walking out
  of threading/queue/this module) is wrapped in a tracking proxy.
  Locks created by third-party/stdlib internals stay native, which
  bounds overhead and noise.  ``threading.Condition()`` and
  ``threading.Event()`` create their inner lock through the patched
  factory, so condition waits release/reacquire through the proxy and
  the held-set stays truthful across ``wait()``.
- ``queue.Queue`` is replaced by a subclass that models the blocking
  hand-off as pseudo-resources: a bounded ``put`` **waits for**
  ``space:Q`` (edge ``held-lock -> space:Q``), a ``get`` under a lock
  **frees** it (edge ``space:Q -> that lock``); symmetrically for
  ``items:Q`` on the get side.  A cycle through a pseudo-node is a
  lock-held-across-blocking-queue-op deadlock — the classic
  "``put`` to a bounded queue while holding the lock its consumer
  needs" hazard that a pure lock graph cannot see.  A blocking bounded
  ``put`` from a thread that is itself a recorded consumer of the same
  queue is flagged immediately (``put-to-self``: nobody else will ever
  drain it once it fills).

Lock identity is the **creation site** (file:line), lockdep-style: all
instances born at one site share a node, so per-connection locks
aggregate instead of exploding the graph.  The cost of that choice:
two instances from the same site nested inside each other form a
self-edge, which is reported as a warning, not a failure (instances may
be globally ordered in a way site-granularity cannot prove).

What it cannot see (documented, deliberate): ``multiprocessing``
queues (cross-process), ``queue.SimpleQueue`` (C implementation),
condition-variable wait-for-state cycles that involve no lock or
bounded queue, and locks created before ``install()`` ran — the pytest
plugin (``EDL_TPU_LOCKGRAPH=1`` in ``tests/conftest.py``) installs at
conftest import, before any edl_tpu module is imported.

Run ``python -m edl_tpu.analysis lockgraph-selftest`` for the seeded
proofs, or ``EDL_TPU_LOCKGRAPH=1 python -m pytest tests/`` for a full
audit (report written to ``EDL_TPU_LOCKGRAPH_OUT`` or
``/tmp/edl_lockgraph.json``; the session FAILS on any cycle).
"""

from __future__ import annotations

import json
import os
import queue as queue_mod
import sys
import threading
import traceback

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_QUEUE = queue_mod.Queue

_SKIP_FILES = (os.sep + "threading.py", os.sep + "queue.py")

# code objects of the instrumentation itself (proxies, factories, the
# recorder) — skipped by frame identity, NOT by filename, so locks
# created by code that happens to live in this file (the selftest
# scenarios) still resolve to their true creation site
_INSTR_CODES: set = set()


def _creation_site(extra_skip: int = 0) -> tuple[str, int]:
    """(file, line) of the first frame outside the instrumentation and
    outside threading/queue — the lock's OWNER in user code."""
    frame = sys._getframe(1 + extra_skip)
    while frame is not None:
        code = frame.f_code
        if code not in _INSTR_CODES \
                and not code.co_filename.endswith(_SKIP_FILES):
            return code.co_filename, frame.f_lineno
        frame = frame.f_back
    return "<unknown>", 0


def _site_key(site: tuple[str, int]) -> str:
    fn, line = site
    # repo-relative when possible: stable across hosts, readable reports
    for marker in ("edl_tpu" + os.sep, "tests" + os.sep):
        idx = fn.rfind(marker)
        if idx >= 0:
            fn = fn[idx:]
            break
    return f"{fn.replace(os.sep, '/')}:{line}"


class LockGraph:
    """The recorder: per-thread held sets, first-seen edges w/ stacks."""

    def __init__(self):
        self._mu = _ORIG_LOCK()
        self.active = True
        # tid -> list[[site, lock_id, count, acquire_site]]
        self._held: dict[int, list[list]] = {}
        # (from_site, to_site) -> {"count", "stack_held", "stack_acq"}
        self.edges: dict[tuple[str, str], dict] = {}
        self.hazards: list[dict] = []
        self._hazard_seen: set[tuple] = set()
        self.lock_sites: set[str] = set()

    # -- held-set bookkeeping (all under _mu) -------------------------------

    def _entries(self) -> list[list]:
        tid = threading.get_ident()
        return self._held.setdefault(tid, [])

    def note_waiting(self, site: str, lock_id: int) -> None:
        """A blocking acquire is about to start: record ordering edges
        from every lock this thread already holds.  Re-acquiring the
        SAME instance (RLock re-entry) is not an ordering edge; a
        distinct instance from the same creation site IS — it surfaces
        as a self-edge warning in the report."""
        if not self.active:
            return
        caller = _site_key(_creation_site(1))
        with self._mu:
            for entry in self._entries():
                if entry[1] != lock_id:
                    self._edge(entry[0], site, entry[3], caller)

    def note_acquired(self, site: str, lock_id: int) -> None:
        if not self.active:
            return
        caller = _site_key(_creation_site(1))
        with self._mu:
            entries = self._entries()
            for entry in entries:
                if entry[1] == lock_id:
                    entry[2] += 1
                    return
            entries.append([site, lock_id, 1, caller])

    def note_released(self, site: str, lock_id: int,
                      count: int = 1) -> None:
        del site
        if not self.active:
            return
        with self._mu:
            # the releasing thread may differ from the acquirer
            # (hand-off locks): search every thread's held list
            for entries in self._held.values():
                for i, entry in enumerate(entries):
                    if entry[1] == lock_id:
                        entry[2] -= count
                        if entry[2] <= 0:
                            del entries[i]
                        return

    def held_count(self, lock_id: int) -> int:
        with self._mu:
            for entries in self._held.values():
                for entry in entries:
                    if entry[1] == lock_id:
                        return entry[2]
        return 0

    def _edge(self, a: str, b: str, stack_held: str, stack_acq: str) -> None:
        # caller holds _mu
        key = (a, b)
        rec = self.edges.get(key)
        if rec is None:
            try:
                frame = sys._getframe(3)
            except ValueError:  # pragma: no cover - shallow stack
                frame = None
            self.edges[key] = {"count": 1, "held_at": stack_held,
                               "acquired_at": stack_acq,
                               "stack": "".join(traceback.format_stack(
                                   frame, limit=12))}
        else:
            rec["count"] += 1

    # -- queue modeling -----------------------------------------------------

    def note_queue_put(self, qsite: str, bounded: bool, block: bool,
                       self_put: bool = False) -> None:
        """`self_put` is computed by the queue INSTANCE (the putting
        thread previously got from this very queue object) — site-level
        consumer tracking would alias every per-connection queue born on
        one line and convict on OS thread-id reuse across instances."""
        if not self.active:
            return
        caller = _site_key(_creation_site(1))
        with self._mu:
            held = [e for e in self._entries()]
            if bounded and block:
                if self_put:
                    key = ("put-to-self", qsite, caller)
                    if key not in self._hazard_seen:
                        self._hazard_seen.add(key)
                        self.hazards.append({
                            "kind": "put-to-self",
                            "queue": qsite, "at": caller,
                            "detail": "blocking put on a bounded queue "
                                      "from a thread that also consumes "
                                      "it — self-deadlock once the queue "
                                      "fills",
                            "stack": "".join(traceback.format_stack(
                                sys._getframe(2), limit=12))})
                for entry in held:
                    self._edge(entry[0], f"space:{qsite}", entry[3], caller)
            # producing items while holding these locks: draining the
            # queue transitively depends on them
            for entry in held:
                self._edge(f"items:{qsite}", entry[0], caller, entry[3])

    def note_queue_get(self, qsite: str, block: bool) -> None:
        if not self.active:
            return
        caller = _site_key(_creation_site(1))
        with self._mu:
            held = [e for e in self._entries()]
            if block:
                for entry in held:
                    self._edge(entry[0], f"items:{qsite}", entry[3], caller)
            # freeing space while holding these locks
            for entry in held:
                self._edge(f"space:{qsite}", entry[0], caller, entry[3])

    # -- analysis -----------------------------------------------------------

    def cycles(self) -> list[list[str]]:
        """Strongly connected components of size >= 2 (Tarjan,
        iterative).  Self-edges are excluded here and reported as
        warnings by ``report()``."""
        graph: dict[str, list[str]] = {}
        for (a, b) in self.edges:
            if a != b:
                graph.setdefault(a, []).append(b)
                graph.setdefault(b, [])
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        for root in sorted(graph):
            if root in index:
                continue
            work = [(root, iter(graph[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(graph[nxt])))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        member = stack.pop()
                        on_stack.discard(member)
                        scc.append(member)
                        if member == node:
                            break
                    if len(scc) >= 2:
                        sccs.append(sorted(scc))
        return sccs

    def report(self) -> dict:
        cycles = self.cycles()
        cycle_edges = []
        for scc in cycles:
            members = set(scc)
            for (a, b), rec in sorted(self.edges.items()):
                if a in members and b in members:
                    cycle_edges.append({
                        "from": a, "to": b, "count": rec["count"],
                        "held_at": rec["held_at"],
                        "acquired_at": rec["acquired_at"],
                        "stack": rec["stack"]})
        self_edges = [{"site": a, "count": rec["count"],
                       "stack": rec["stack"]}
                      for (a, b), rec in sorted(self.edges.items())
                      if a == b]
        return {
            "locks_tracked": len(self.lock_sites),
            "edges": len(self.edges),
            "cycles": cycles,
            "cycle_edges": cycle_edges,
            "hazards": self.hazards,
            "self_edge_warnings": self_edges,
            "ok": not cycles and not self.hazards,
        }


# --------------------------------------------------------------------------
# proxies


class _PlainTrackedLock:
    """Proxy around a plain ``Lock``; same blocking semantics, every
    blocking acquire recorded against the holder's held-set.

    Deliberately does NOT define ``_release_save``/``_acquire_restore``:
    ``threading.Condition`` probes for them and, absent, falls back to
    ``acquire``/``release`` — the tracked proxy methods — so condition
    waits keep the held-set truthful."""

    __slots__ = ("_inner", "_site", "_graph")

    def __init__(self, inner, site: str, graph: LockGraph):
        self._inner = inner
        self._site = site
        self._graph = graph

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._graph.note_waiting(self._site, id(self))
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._graph.note_acquired(self._site, id(self))
        return got

    def release(self) -> None:
        self._graph.note_released(self._site, id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<tracked {self._inner!r} @ {self._site}>"

    def _is_owned(self):
        # plain-Lock probe (mirrors threading.Condition's fallback)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()


class _TrackedRLock(_PlainTrackedLock):
    """RLock flavor: Condition.wait() releases ALL recursion levels via
    ``_release_save`` — route it through the proxy so the held-set
    reflects the park (and the re-acquire records ordering edges)."""

    __slots__ = ()

    def _release_save(self):
        state = self._inner._release_save()
        count = state[0] if isinstance(state, tuple) else 1
        self._graph.note_released(self._site, id(self), count=count)
        return state

    def _acquire_restore(self, state) -> None:
        self._graph.note_waiting(self._site, id(self))
        self._inner._acquire_restore(state)
        count = state[0] if isinstance(state, tuple) else 1
        for _ in range(count):
            self._graph.note_acquired(self._site, id(self))

    def _is_owned(self):
        return self._inner._is_owned()


class _Installer:
    def __init__(self, graph: LockGraph, wrap_all: bool,
                 markers: tuple[str, ...]):
        self.graph = graph
        self.wrap_all = wrap_all
        self.markers = markers

    def _should_wrap(self, site_file: str) -> bool:
        if self.wrap_all:
            return True
        return any(m in site_file for m in self.markers)

    def make_lock(self):
        site = _creation_site(1)
        if not self._should_wrap(site[0]):
            return _ORIG_LOCK()
        key = _site_key(site)
        self.graph.lock_sites.add(key)
        return _PlainTrackedLock(_ORIG_LOCK(), key, self.graph)

    def make_rlock(self):
        site = _creation_site(1)
        if not self._should_wrap(site[0]):
            return _ORIG_RLOCK()
        key = _site_key(site)
        self.graph.lock_sites.add(key)
        return _TrackedRLock(_ORIG_RLOCK(), key, self.graph)

    def make_queue_class(self):
        installer = self

        class TrackedQueue(_ORIG_QUEUE):
            def __init__(self, maxsize: int = 0):
                site = _creation_site(1)
                self._lg_site = (_site_key(site)
                                 if installer._should_wrap(site[0])
                                 else None)
                # tids that have EVER gotten from THIS instance —
                # per-instance on purpose (site-level tracking aliases
                # per-connection queues and convicts on tid reuse)
                self._lg_getters: set[int] = set()
                super().__init__(maxsize)

            def put(self, item, block: bool = True,
                    timeout: float | None = None):
                if self._lg_site is not None:
                    installer.graph.note_queue_put(
                        self._lg_site, bounded=self.maxsize > 0,
                        block=block,
                        self_put=threading.get_ident()
                        in self._lg_getters)
                return super().put(item, block, timeout)

            def get(self, block: bool = True,
                    timeout: float | None = None):
                if self._lg_site is not None:
                    self._lg_getters.add(threading.get_ident())
                    installer.graph.note_queue_get(self._lg_site,
                                                   block=block)
                return super().get(block, timeout)

        _INSTR_CODES.update({TrackedQueue.__init__.__code__,
                             TrackedQueue.put.__code__,
                             TrackedQueue.get.__code__})
        return TrackedQueue


_INSTR_CODES.update(
    fn.__code__ for fn in (
        LockGraph.note_waiting, LockGraph.note_acquired,
        LockGraph.note_released, LockGraph.note_queue_put,
        LockGraph.note_queue_get, LockGraph._edge,
        _PlainTrackedLock.acquire, _PlainTrackedLock.release,
        _PlainTrackedLock.__enter__, _PlainTrackedLock.__exit__,
        _PlainTrackedLock._is_owned,
        _TrackedRLock._release_save, _TrackedRLock._acquire_restore,
        _Installer.make_lock, _Installer.make_rlock,
    ))

# Installers form a STACK: a scoped install (the selftest, unit tests)
# over a session-wide one (the pytest plugin) must record into its OWN
# fresh graph — a seeded ABBA scenario polluting the session graph
# would fail the whole run — and popping it must RESUME the outer
# instrumentation, not strip it. Locks already wrapped keep recording
# into the graph they were born under either way.
_STACK: list[_Installer] = []


def _apply(installer: _Installer | None) -> None:
    if installer is None:
        threading.Lock = _ORIG_LOCK               # type: ignore[misc]
        threading.RLock = _ORIG_RLOCK             # type: ignore[misc]
        queue_mod.Queue = _ORIG_QUEUE             # type: ignore[misc]
    else:
        threading.Lock = installer.make_lock      # type: ignore[misc]
        threading.RLock = installer.make_rlock    # type: ignore[misc]
        queue_mod.Queue = installer.queue_class   # type: ignore[misc]


def install(wrap_all: bool = False,
            markers: tuple[str, ...] = ("edl_tpu", "tests")
            ) -> LockGraph:
    """Patch the factories; locks/queues created FROM NOW ON in files
    matching `markers` are tracked.  Returns a FRESH graph (nesting
    allowed — see the stack note above).  Call as early as possible
    (before edl_tpu imports) so module-level locks are caught."""
    installer = _Installer(LockGraph(), wrap_all, markers)
    installer.queue_class = installer.make_queue_class()
    _STACK.append(installer)
    _apply(installer)
    return installer.graph


def uninstall() -> None:
    """Pop the innermost install: its graph stops recording and the
    previous instrumentation (or the original factories) resumes."""
    if not _STACK:
        return
    top = _STACK.pop()
    top.graph.active = False
    _apply(_STACK[-1] if _STACK else None)


def plugin_enabled() -> bool:
    """The EDL_TPU_LOCKGRAPH=1 contract consumed by tests/conftest.py."""
    from edl_tpu.utils import config as _cfg
    return _cfg.env_flag("EDL_TPU_LOCKGRAPH", False)


def default_report_path() -> str:
    from edl_tpu.utils import config as _cfg
    return _cfg.env_str("EDL_TPU_LOCKGRAPH_OUT",
                        "/tmp/edl_lockgraph.json") or \
        "/tmp/edl_lockgraph.json"


def write_report(graph: LockGraph, path: str) -> dict:
    rep = graph.report()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(rep, f, indent=2, sort_keys=True)
    return rep


def render_failure(rep: dict) -> str:
    lines = ["lockgraph: lock-order violations detected", ""]
    for cyc in rep["cycles"]:
        lines.append("  cycle: " + " -> ".join(cyc + [cyc[0]]))
    for edge in rep["cycle_edges"]:
        lines.append(f"\n  edge {edge['from']} -> {edge['to']} "
                     f"(seen {edge['count']}x)")
        lines.append(f"    holder acquired at {edge['held_at']}, "
                     f"then acquired {edge['to']} at "
                     f"{edge['acquired_at']}")
        lines.append("    first-seen stack:\n" + "\n".join(
            "      " + ln for ln in edge["stack"].splitlines()))
    for hz in rep["hazards"]:
        lines.append(f"\n  hazard [{hz['kind']}] on {hz['queue']} at "
                     f"{hz['at']}: {hz['detail']}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# selftest


def selftest(verbose: bool = True) -> int:
    """Three seeded scenarios prove the detector's teeth:

    1. an ABBA pair (two threads, opposite nesting) -> cycle;
    2. a lock held across a blocking ``put`` to a bounded queue whose
       consumer takes the same lock -> cycle through the pseudo-node,
       plus the put-to-self direct hazard on a second queue;
    3. a well-ordered control (consistent nesting, lock-free queue
       hand-off) -> clean graph.

    The scenarios run the threads SEQUENTIALLY — the whole point of a
    lock-order graph is that it convicts on ordering evidence without
    needing the unlucky interleaving to actually happen.
    """
    failures: list[str] = []

    # 1: ABBA --------------------------------------------------------------
    graph = install(wrap_all=True)
    try:
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def t1():
            with lock_a:
                with lock_b:
                    pass

        def t2():
            with lock_b:
                with lock_a:
                    pass

        for fn in (t1, t2):
            th = threading.Thread(target=fn)
            th.start()
            th.join()
        rep = graph.report()
        if not rep["cycles"]:
            failures.append("ABBA cycle NOT detected")
        elif verbose:
            print("selftest 1 OK: ABBA cycle detected:",
                  rep["cycles"][0])
    finally:
        uninstall()

    # 2: lock held across bounded put-to-self / put-vs-consumer ------------
    graph = install(wrap_all=True)
    try:
        lock = threading.Lock()
        q = queue_mod.Queue(maxsize=1)

        def consumer():
            with lock:          # consumer needs `lock` to drain
                q.get()

        def producer():
            with lock:          # ...which the producer holds across put
                q.put("x")

        pth = threading.Thread(target=producer)
        pth.start()
        pth.join()
        cth = threading.Thread(target=consumer)
        cth.start()
        cth.join()

        # and the direct self-hazard: one thread both gets and
        # block-puts on the same bounded queue
        q2 = queue_mod.Queue(maxsize=4)
        q2.put("seed")
        q2.get()
        q2.put("again")

        rep = graph.report()
        pseudo_cycle = any(
            any(node.startswith(("space:", "items:")) for node in cyc)
            for cyc in rep["cycles"])
        if not pseudo_cycle:
            failures.append(
                "lock-held-across-queue.put cycle NOT detected")
        elif verbose:
            print("selftest 2 OK: queue hand-off cycle detected:",
                  [c for c in rep["cycles"]
                   if any(n.startswith(("space:", "items:"))
                          for n in c)][0])
        if not any(h["kind"] == "put-to-self" for h in rep["hazards"]):
            failures.append("put-to-self hazard NOT detected")
        elif verbose:
            print("selftest 2 OK: put-to-self hazard flagged")
    finally:
        uninstall()

    # 3: clean control ------------------------------------------------------
    graph = install(wrap_all=True)
    try:
        outer = threading.Lock()
        inner = threading.Lock()
        q = queue_mod.Queue()   # unbounded: put never blocks

        def worker():
            with outer:
                with inner:
                    q.put("x")
            q.get()

        for _ in range(2):
            th = threading.Thread(target=worker)
            th.start()
            th.join()
        rep = graph.report()
        if rep["cycles"] or rep["hazards"]:
            failures.append(
                f"clean scenario convicted: cycles={rep['cycles']} "
                f"hazards={rep['hazards']}")
        elif verbose:
            print("selftest 3 OK: well-ordered scenario stays clean "
                  f"({rep['edges']} edges recorded)")
    finally:
        uninstall()

    if failures:
        for f in failures:
            print("lockgraph selftest FAILED:", f, file=sys.stderr)
        return 1
    if verbose:
        print("lockgraph selftest: all scenarios passed")
    return 0


if __name__ == "__main__":
    sys.exit(selftest())
