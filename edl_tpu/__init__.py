"""edl_tpu — TPU-native elastic deep learning framework.

A from-scratch JAX/XLA implementation of the capabilities of PaddlePaddle EDL
(reference: tinyma123/edl v0.3.1): checkpoint-based elastic collective training
over TPU device meshes, and elastic knowledge distillation with a
service-discovery/balancer layer.

Layer map (ours; cf. reference SURVEY.md §1):

    coord/       key/lease/watch coordination store + service registry
                 (capability of reference discovery/etcd_client.py,
                 pkg/master/etcd_client.go — native C++ server in native/)
    collective/  elastic job orchestration: pod rank claim, watcher, barrier,
                 trainer process management, JobServer/JobClient
                 (reference collective/launch.py + absent demo pkg)
    train/       train loop, checkpoint/resume, LR schedules
                 (reference train_with_fleet.py + fleet save/load_check_point)
    parallel/    mesh building, sharding rules, ring-attention SP
                 (reference: NCCL data plane -> XLA collectives over ICI)
    distill/     DistillReader + teacher discovery/balancing + TPU teacher server
                 (reference distill/, discovery/)
    models/      ResNet50[_vd], VGG, BOW/CNN text, DeepFM, transformer — flax
    ops/         TPU kernels: Pallas flash attention, streamed-vocab CE
    data/        sharded input pipelines (in-memory / file / remote-served
                 sources), elastic task-dispenser master + task data loader
                 (reference pkg/master/service.go, utils/data_server.py),
                 seed-per-pass shuffle
    utils/       config/env overlay, logging, net, timeline profiler,
                 remote FS (gs://, hdfs://) + checkpoint mirroring
    examples/    fit_a_line, elastic/multipod demos, imagenet_train,
                 mnist/nlp distill, ctr_train
"""

__version__ = "0.1.0"
