"""edl_tpu — TPU-native elastic deep learning framework.

A from-scratch JAX/XLA implementation of the capabilities of PaddlePaddle EDL
(reference: tinyma123/edl v0.3.1): checkpoint-based elastic collective training
over TPU device meshes, and elastic knowledge distillation with a
service-discovery/balancer layer.

Layer map (ours; cf. reference SURVEY.md §1):

    coord/       key/lease/watch coordination store + service registry
                 (capability of reference discovery/etcd_client.py,
                 pkg/master/etcd_client.go — native C++ server in native/)
    collective/  elastic job orchestration: pod rank claim, watcher, barrier,
                 trainer process management, JobServer/JobClient
                 (reference collective/launch.py + absent demo pkg)
    train/       train loop, checkpoint/resume, LR schedules
                 (reference train_with_fleet.py + fleet save/load_check_point)
    parallel/    mesh building, sharding rules, ring-attention SP
                 (reference: NCCL data plane -> XLA collectives over ICI)
    distill/     DistillReader + teacher discovery/balancing + TPU teacher server
                 (reference distill/, discovery/)
    master/      elastic data-sharding task dispenser
                 (reference pkg/master/service.go intent)
    models/      ResNet50[_vd], VGG, BOW, DeepFM, transformer — flax
    data/        sharded input pipelines, seed-per-pass shuffle
    ops/         pallas TPU kernels
"""

__version__ = "0.1.0"
