"""Fused causal attention: Pallas TPU forward AND backward kernels.

The hot op of the transformer path, written for the hardware instead of
leaving the S^2 score tensor to XLA: the kernel streams K/V blocks
through VMEM against a resident Q block, keeping the online-softmax
running (max, denominator) in registers/VMEM — scores never exist in HBM
at any size, and the two matmuls per block land on the MXU with fp32
accumulation. Causal skip: K/V blocks entirely in a Q block's future are
never read (the standard flash-attention trick, halving the work).

Backward: the flash recipe (Dao et al.) with the saved log-sum-exp and
delta = rowsum(dO * O), as two Pallas kernels — dK/dV (KV block
resident, Q streamed) and dQ (Q block resident, KV streamed) — with the
causal block skip in both directions. `_bwd_blockwise`, the plain-XLA
scan version, is kept as the reference oracle for the kernel parity
tests; profiling showed it at ~29% of LM step time for ~6% of model
FLOPs (it masks instead of skipping and round-trips fp32 score tensors
through HBM), which is what motivated the kernels.

Layout contract: (B, S, H, D) in, (B, S, H, D) out (the transformer's
native layout; the kernel grid works on (B*H, S, D) views). On non-TPU
backends both directions dispatch to compiled XLA blockwise paths
(`_fwd_blockwise` / `_bwd_blockwise`) — interpret-mode Pallas is orders
of magnitude slower and would throttle the CPU elastic/multipod worlds.
The parity tests force the kernels through the same public API via
`force_interpret_kernels()`.

No reference counterpart (its models are CNNs + served ERNIE); this is
the tpu-first half of the long-context story, composing with
parallel/ring_attention.py which shards S over the mesh and calls a
per-shard attention on each block pair.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, blk_k: int,
                scale: float, causal: bool):
    """One (batch*head, q-block) program: stream K/V blocks online.

    q_ref: (1, BLK_Q, D); k_ref/v_ref: (1, S, D); o_ref: (1, BLK_Q, D);
    lse_ref: (1, BLK_Q, 1) log-sum-exp for the backward (trailing 1 dim:
    TPU block shapes need the last dims tileable-or-full).
    """
    _, blk_q, d = q_ref.shape
    s = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, 1), 0)

    def body(ki, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
        sblk = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            kv_pos = ki * blk_k + lax.broadcasted_iota(
                jnp.int32, (1, blk_k), 1)
            sblk = jnp.where(q_pos >= kv_pos, sblk, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=-1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.dot(p, v_blk,
                               preferred_element_type=jnp.float32)
        return o, m_new, l

    o0 = jnp.zeros((blk_q, d), jnp.float32)
    m0 = jnp.full((blk_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    if causal:
        # blocks strictly after this q block never contribute
        n_blocks = lax.div((qi + 1) * blk_q + blk_k - 1, blk_k)
    else:
        n_blocks = s // blk_k
    o, m, l = lax.fori_loop(0, n_blocks, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _fwd(q, k, v, *, blk_q: int, blk_k: int, scale: float, causal: bool,
         interpret: bool):
    b, s, h, d = q.shape
    # (B, S, H, D) -> (B*H, S, D) program-per-head views
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    grid = (b * h, s // blk_q)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, blk_k=blk_k, scale=scale,
                          causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    o = o.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return o, lse[..., 0]


def _fwd_blockwise(q, k, v, *, blk: int, scale: float, causal: bool):
    """Flash forward in plain XLA (KV-block scan with the online
    softmax) — the off-TPU fallback. Returns (o, lse) exactly as `_fwd`
    does: o (B,S,H,D) in q.dtype, lse (B*H, S) fp32."""
    b, s, h, d = q.shape
    q32 = q.astype(jnp.float32)
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    q_pos = jnp.arange(s)

    def kv_step(carry, ki):
        m, l, acc = carry  # (B,H,S), (B,H,S), (B,S,H,D)
        ksl = lax.dynamic_slice_in_dim(k32, ki * blk, blk, axis=1)
        vsl = lax.dynamic_slice_in_dim(v32, ki * blk, blk, axis=1)
        sblk = jnp.einsum("bqhd,bkhd->bhqk", q32, ksl,
                          preferred_element_type=jnp.float32) * scale
        if causal:
            kv_pos = ki * blk + jnp.arange(blk)
            mask = q_pos[:, None] >= kv_pos[None, :]
            sblk = jnp.where(mask[None, None], sblk, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=-1))
        p = jnp.exp(sblk - m_new[..., None])
        corr = jnp.exp(m - m_new)  # (B,H,S)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = (acc * corr.transpose(0, 2, 1)[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", p, vsl,
                            preferred_element_type=jnp.float32))
        return (m_new, l, acc), None

    init = (jnp.full((b, h, s), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, s), jnp.float32),
            jnp.zeros((b, s, h, d), jnp.float32))
    (m, l, acc), _ = lax.scan(kv_step, init, jnp.arange(s // blk))
    l = jnp.maximum(l, 1e-30)  # same guard as the kernel
    o = (acc / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    lse = (m + jnp.log(l)).reshape(b * h, s)
    return o, lse


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, rt_ref,
                     dk_ref, dv_ref, *, blk_q: int, scale: float,
                     causal: bool):
    """One (batch*head, kv-block) program: K/V block resident, stream Q
    blocks (causal: only blocks that can see this KV block), accumulate
    dK/dV in fp32 VMEM.

    q_ref/do_ref: (1, S, D); k_ref/v_ref/dk_ref/dv_ref: (1, BLK_K, D);
    lse_ref/rt_ref: (1, S, 1) fp32 — lse from the forward; rt is the
    row term delta - dlse (delta = rowsum(dO*O)), precomputed in XLA so
    one kernel serves both the plain and the lse-cotangent vjp.
    """
    _, blk_k, d = k_ref.shape
    s = q_ref.shape[1]
    ki = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)
    kv_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32, (1, blk_k), 1)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * blk_q, blk_q), :]
        rt = rt_ref[0, pl.ds(qi * blk_q, blk_q), :]
        sblk = jnp.dot(q, k_blk.T,
                       preferred_element_type=jnp.float32) * scale
        p = jnp.exp(sblk - lse)  # (blk_q, blk_k)
        if causal:
            q_pos = qi * blk_q + lax.broadcasted_iota(
                jnp.int32, (blk_q, 1), 0)
            p = jnp.where(q_pos >= kv_pos, p, 0.0)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - rt) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # the first q block that can see any row of this kv block
        q_start = lax.div(ki * blk_k, blk_q)
    else:
        q_start = 0
    zeros = jnp.zeros((blk_k, d), jnp.float32)
    dk, dv = lax.fori_loop(q_start, s // blk_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, rt_ref, dq_ref,
                   *, blk_k: int, scale: float, causal: bool):
    """One (batch*head, q-block) program: Q block resident, stream KV
    blocks (causal skip as in the forward), accumulate dQ."""
    _, blk_q, d = q_ref.shape
    s = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    rt = rt_ref[0]
    q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, 1), 0)

    def body(ki, dq):
        k_blk = k_ref[0, pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
        sblk = jnp.dot(q, k_blk.T,
                       preferred_element_type=jnp.float32) * scale
        p = jnp.exp(sblk - lse)
        if causal:
            kv_pos = ki * blk_k + lax.broadcasted_iota(
                jnp.int32, (1, blk_k), 1)
            p = jnp.where(q_pos >= kv_pos, p, 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - rt) * scale
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    if causal:
        n_blocks = lax.div((qi + 1) * blk_q + blk_k - 1, blk_k)
    else:
        n_blocks = s // blk_k
    dq = lax.fori_loop(0, n_blocks, body,
                       jnp.zeros((blk_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, *, blk_q: int, blk_k: int,
                scale: float, causal: bool, dlse, interpret: bool):
    """Pallas flash backward: same math as `_bwd_blockwise` (the XLA
    reference used by the parity tests) but with scores recomputed in
    VMEM — nothing S^2-shaped touches HBM — and the causal block skip
    in BOTH directions (the XLA scan masks instead of skipping, doing
    2x the needed work). The trace that motivated this: the scan
    backward was ~29% of LM step time for ~6% of model FLOPs."""
    b, s, h, d = q.shape
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    dot = do.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    # row term = delta - dlse, delta_i = rowsum(dO_i * O_i): cheap
    # elementwise XLA; folding it here keeps the kernels single-purpose
    delta = jnp.sum(dot.astype(jnp.float32)
                    * o.transpose(0, 2, 1, 3).reshape(b * h, s, d)
                    .astype(jnp.float32), axis=-1, keepdims=True)
    rt = delta if dlse is None else delta - dlse[..., None].astype(
        jnp.float32)
    lse3 = lse[..., None]

    common_in = [qt, kt, vt, dot, lse3, rt]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, blk_q=blk_q, scale=scale,
                          causal=causal),
        grid=(b * h, s // blk_k),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, blk_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, blk_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, s, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, blk_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s, d), v.dtype),
        ],
        interpret=interpret,
    )(*common_in)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, blk_k=blk_k, scale=scale,
                          causal=causal),
        grid=(b * h, s // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(*common_in)

    def back(x):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return back(dq), back(dk), back(dv)


def _bwd_blockwise(q, k, v, o, lse, do, *, blk: int, scale: float,
                   causal: bool, dlse=None):
    """Flash backward in plain XLA, scanning KV blocks. All (B,S,H,D).

    With `dlse` (a (B*H, S) cotangent on the log-sum-exp output), the
    score gradient gains the softmax term: d(lse)/d(s_ij) = p_ij, so
    ds += p * dlse_row — this is what lets consumers of (o, lse)
    (the lse-combine in ring attention) differentiate through both.
    """
    b, s, h, d = q.shape
    q32 = q.astype(jnp.float32)
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # delta_i = rowsum(dO_i * O_i)  (B,S,H)
    delta = jnp.sum(do32 * o.astype(jnp.float32), axis=-1)
    lse_b = lse.reshape(b, h, s).transpose(0, 2, 1)  # (B,S,H)
    dlse_bh = (None if dlse is None
               else dlse.reshape(b, h, s).astype(jnp.float32))  # (B,H,S)

    q_pos = jnp.arange(s)

    def kv_step(carry, ki):
        dq_acc = carry
        ksl = lax.dynamic_slice_in_dim(k32, ki * blk, blk, axis=1)
        vsl = lax.dynamic_slice_in_dim(v32, ki * blk, blk, axis=1)
        # scores for ALL q rows vs this kv block: (B,H,S,blk)
        sblk = jnp.einsum("bqhd,bkhd->bhqk", q32, ksl,
                          preferred_element_type=jnp.float32) * scale
        if causal:
            kv_pos = ki * blk + jnp.arange(blk)
            mask = q_pos[:, None] >= kv_pos[None, :]
            sblk = jnp.where(mask[None, None], sblk, _NEG_INF)
        p = jnp.exp(sblk - lse_b.transpose(0, 2, 1)[..., None])  # (B,H,S,blk)
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, do32,
                            preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do32, vsl,
                        preferred_element_type=jnp.float32)
        # dL/ds_ij = p_ij * (dp_ij - delta_i + dlse_i); the trailing
        # *scale converts to the gradient w.r.t. the unscaled q.k
        row_term = delta.transpose(0, 2, 1)[..., None]
        if dlse_bh is not None:
            row_term = row_term - dlse_bh[..., None]
        ds = p * (dp - row_term) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, ksl,
                                     preferred_element_type=jnp.float32)
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32,
                            preferred_element_type=jnp.float32)
        return dq_acc, (dk_blk, dv_blk)

    n_blocks = s // blk
    dq, (dk_blocks, dv_blocks) = lax.scan(
        kv_step, jnp.zeros_like(q32), jnp.arange(n_blocks))
    dk = dk_blocks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    dv = dv_blocks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _fit_block(s: int, want: int) -> int:
    """Largest MXU-friendly block <= want that divides s (128-granular,
    so any 128-divisible sequence works — e.g. S=640 gets 128 blocks)."""
    if want >= s:
        if s % 128 == 0 or s <= 512:
            return s
    for b in (want, 512, 384, 256, 128):
        if b <= want and s % b == 0:
            return b
    raise ValueError(f"sequence {s} not divisible by any block size "
                     f"<= {want} (pad the sequence to a multiple of 128)")


_FORCE_INTERPRET = False


@contextlib.contextmanager
def force_interpret_kernels():
    """Test hook: run the Pallas kernels (fwd AND bwd) in interpret mode
    even off-TPU — the parity tests compare them against the XLA
    blockwise paths through the public API."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = True
    try:
        yield
    finally:
        _FORCE_INTERPRET = False


def _use_kernels() -> bool:
    """Off-TPU the compiled XLA blockwise paths run instead of
    interpret-mode Pallas (orders of magnitude slower — it would
    throttle the CPU elastic/multipod worlds)."""
    return jax.default_backend() == "tpu" or _FORCE_INTERPRET


def _fwd_dispatch(q, k, v, blk_q, blk_k, scale, causal):
    if not _use_kernels():
        return _fwd_blockwise(q, k, v, blk=blk_k, scale=scale,
                              causal=causal)
    return _fwd(q, k, v, blk_q=blk_q, blk_k=blk_k, scale=scale,
                causal=causal, interpret=jax.default_backend() != "tpu")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, blk_q, blk_k, scale, causal):
    return _fwd_dispatch(q, k, v, blk_q, blk_k, scale, causal)


def _flash_lse_fwd(q, k, v, blk_q, blk_k, scale, causal):
    o, lse = _fwd_dispatch(q, k, v, blk_q, blk_k, scale, causal)
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_bwd(blk_q, blk_k, scale, causal, res, cotangents):
    q, k, v, o, lse = res
    do, dlse = cotangents
    if not _use_kernels():
        return _bwd_blockwise(q, k, v, o, lse, do, blk=blk_k,
                              scale=scale, causal=causal, dlse=dlse)
    return _bwd_pallas(q, k, v, o, lse, do, blk_q=blk_q, blk_k=blk_k,
                       scale=scale, causal=causal, dlse=dlse,
                       interpret=jax.default_backend() != "tpu")


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True, scale: float | None = None,
                        block_q: int = 512, block_k: int = 512
                        ) -> tuple[jax.Array, jax.Array]:
    """flash_attention that ALSO returns the per-row log-sum-exp
    ((B, H*... reshaped) -> (B, S, H)) — the combinable statistic for
    composing partial attentions (ring attention's per-block kernel:
    two normalized outputs merge exactly via their lse weights).
    Fully differentiable through both outputs.
    """
    b, s, h, d = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(f"q/k/v shape mismatch: {q.shape} {k.shape} "
                         f"{v.shape}")
    blk_q = _fit_block(s, block_q)
    blk_k = _fit_block(s, block_k)
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    o, lse = _flash_lse(q, k, v, blk_q, blk_k, scale, causal)
    return o, lse.reshape(b, h, s).transpose(0, 2, 1)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: float | None = None,
                    block_q: int = 512, block_k: int = 512) -> jax.Array:
    """Fused causal attention. q/k/v: (B, S, H, D) -> (B, S, H, D).

    Blocks auto-fit any 128-divisible sequence (pad upstream otherwise —
    the transformer's static max_len already guarantees this). One
    custom_vjp serves this and `flash_attention_lse`: the unused lse
    output's cotangent is zero, which `_bwd_blockwise` folds away.
    """
    return flash_attention_lse(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k)[0]
