"""Device-side batch augmentation: jitted crop/flip/normalize (+ mixup).

The other half of the packed-records feed path (`data/packed_records.py`):
the host ships raw fixed-stride bytes and the augmentation that used to
burn host cores (`pipeline.random_flip_lr` / `random_crop`) runs as a
jitted program on the accelerator, dispatched right after placement so
it overlaps the previous step instead of gating batch production.

Seed contract — the loader's determinism invariant extended on-device:

- The parent draws ONE batch seed per step, in step order, from the
  per-(epoch, rank) generator — exactly the draw the host batch
  transforms consume today (`DataLoader._epoch_descriptors`).  With
  `DataLoader(emit_batch_seed=True)` that same draw rides the batch as
  a 0-d uint32 under ``AUGMENT_SEED_KEY`` (through the inline, thread
  and shm-ring mp paths unchanged — it is part of the descriptor's pure
  function, so all modes stay bit-identical).
- The device op folds it in: ``key = jax.random.fold_in(PRNGKey(
  base_seed), batch_seed)``; decisions are drawn (flip, y, x) in the
  SAME order as the host pipeline draws them.
- Host<->device equivalence is at the TRANSFORM level: given the same
  decisions, host and device produce bit-identical pixels
  (`apply_flip_lr` / `apply_crop` vs `random_flip_lr` / `random_crop`,
  asserted by tests/test_packed_records.py).  The decision BITS differ
  by backend — numpy's PCG64 and jax's Threefry are different
  generators — so host-augmented and device-augmented runs are two
  distinct-but-equally-distributed deterministic streams, each exactly
  replayable from (seed, epoch, rank, step).  `host_crop_flip_decisions`
  replays the host pipeline's draws for the equivalence test and for
  anyone who needs to reproduce one stream on the other backend.

Mixup was already device-side (derived from `fold_in(seed, state.step)`
inside the jitted step); it lives here now with the rest of the
augmentation ops and `train/classification.py` re-exports it.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

AUGMENT_SEED_KEY = "augment_seed"

# Per-channel ImageNet statistics (reference img_tool.py:116-117), scaled
# to the uint8 range because pixels ship as 1 byte/channel and normalize
# ON DEVICE (the DALI recipe: float32 pixels would 4x the H2D bytes).
IMAGENET_MEAN = (0.485 * 255.0, 0.456 * 255.0, 0.406 * 255.0)
IMAGENET_STD = (0.229 * 255.0, 0.224 * 255.0, 0.225 * 255.0)


def normalize_image(images: jax.Array, mode: str | None) -> jax.Array:
    """On-device pixel normalization for uint8 NHWC batches.

    None: passthrough (floats already normalized on host — the npz path);
    'imagenet': per-channel (x - mean)/std with the reference's
    constants; 'unit': x*(2/255) - 1."""
    if mode is None:
        return images
    if mode == "imagenet":
        mean = jnp.asarray(IMAGENET_MEAN, jnp.float32)
        std = jnp.asarray(IMAGENET_STD, jnp.float32)
        return (images.astype(jnp.float32) - mean) / std
    if mode == "unit":
        return images.astype(jnp.float32) * (2.0 / 255.0) - 1.0
    raise ValueError(f"unknown normalize mode {mode!r}")


def mixup(key: jax.Array, images: jax.Array, targets: jax.Array,
          alpha: float) -> tuple[jax.Array, jax.Array]:
    """Mixup a batch with a Beta(alpha, alpha) coefficient.

    One lambda per batch (the reference's recipe) + a random permutation of
    the batch as the mixing partner. Static shapes; jit-safe.
    """
    k1, k2 = jax.random.split(key)
    lam = jax.random.beta(k1, alpha, alpha)
    perm = jax.random.permutation(k2, images.shape[0])
    mixed_x = lam * images + (1.0 - lam) * images[perm]
    mixed_y = lam * targets + (1.0 - lam) * targets[perm]
    return mixed_x.astype(images.dtype), mixed_y


# -- transform appliers (decision -> pixels; shared by the jitted augment
#    and the host-equivalence test) ----------------------------------------

def apply_flip_lr(images: jax.Array, flip: jax.Array) -> jax.Array:
    """Per-sample horizontal flip (NHWC) by boolean mask — the device
    twin of `pipeline.random_flip_lr`'s `out[flip] = out[flip, :, ::-1]`."""
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :],
                     images)


def apply_crop(images: jax.Array, ys: jax.Array, xs: jax.Array,
               pad: int) -> jax.Array:
    """Pad-and-crop (NHWC) at per-sample (y, x) offsets — the device twin
    of `pipeline.random_crop` (same reflect padding, same window)."""
    n, h, w, c = images.shape
    padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                     mode="reflect")

    def one(img, y, x):
        return jax.lax.dynamic_slice(img, (y, x, 0), (h, w, c))

    return jax.vmap(one)(padded, ys, xs)


def host_crop_flip_decisions(batch_seed: int, n: int, pad: int = 4
                             ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Replay the HOST pipeline's augmentation draws for one batch:
    `transforms=(random_flip_lr, random_crop)` consumes the per-step
    generator as flip (n uniforms), then ys, then xs — in that order.
    Feeding these to `apply_flip_lr`/`apply_crop` reproduces the host
    stream bit-for-bit (the equivalence contract's test hook)."""
    rng = np.random.default_rng(batch_seed)
    flip = rng.random(n) < 0.5
    ys = rng.integers(0, 2 * pad + 1, size=n)
    xs = rng.integers(0, 2 * pad + 1, size=n)
    return flip, ys.astype(np.int32), xs.astype(np.int32)


# -- the jitted augment ------------------------------------------------------

def make_device_augment(*, pad: int = 4, flip: bool = True,
                        crop: bool = True, normalize: str | None = None,
                        base_seed: int = 0, image_key: str = "image"
                        ) -> Callable:
    """Jitted `(batch, seed) -> batch` device augmentation.

    `seed` is the parent-drawn per-step batch seed (a 0-d uint32 — what
    `DataLoader(emit_batch_seed=True)` attaches and
    `prefetch_to_device(augment=...)` / `TrainLoop` pop off the batch
    before placement); it is folded into ``PRNGKey(base_seed)`` so two
    jobs with different base seeds draw independent streams from the
    same loader.  Decisions draw in host order (flip, y, x).  The
    returned batch replaces `image_key` (normalized if `normalize`) and
    carries every other key through untouched.
    """

    @jax.jit
    def augment(batch: dict, seed: jax.Array) -> dict:
        images = batch[image_key]
        n = images.shape[0]
        key = jax.random.fold_in(jax.random.PRNGKey(base_seed), seed)
        k_flip, k_y, k_x = jax.random.split(key, 3)
        if flip:
            images = apply_flip_lr(images,
                                   jax.random.uniform(k_flip, (n,)) < 0.5)
        if crop:
            ys = jax.random.randint(k_y, (n,), 0, 2 * pad + 1)
            xs = jax.random.randint(k_x, (n,), 0, 2 * pad + 1)
            images = apply_crop(images, ys, xs, pad)
        images = normalize_image(images, normalize)
        return {**{k: v for k, v in batch.items() if k != image_key},
                image_key: images}

    return augment
