"""Fused optimizer-update kernels over flat parameter buckets.

One Pallas VMEM pass per bucket: grad + param + moments stream through
VMEM once and the whole momentum-SGD / Adam update (including the
dequant-update-requant round trip when moments are held quantized)
happens in registers, instead of XLA's long chain of elementwise HLOs
that re-reads HBM between every multiply. Buckets come from the same
planner as the DCN gradient path (train/comm.py plan_buckets): flat,
dtype-grouped, lane-padded buffers a few MiB each — well inside VMEM.

Backend split mirrors ops/pack.py exactly: the kernel path runs on TPU
(or under `force_pallas_interpret()` in tests), everywhere else the
plain-XLA expression is used. Both paths are built from the SAME jnp
math helpers (`_sgdm_math`, `_adam_math`, the shared quantize helpers
in ops/pack.py), so interpret-mode kernel output is bitwise-identical
to the XLA fallback by construction — the equivalence the tests pin.

Quantized resident moments (`quant='int8'`/`'fp8'`): between steps a
moment plane lives as TWO int8 payloads + two fp32 scales per bucket —
the symmetric-int8 quantization of the moment itself, plus the
symmetric-int8 quantization of the rounding RESIDUAL (error feedback,
generalizing the r21 residual machinery in train/comm.py). Since
|residual| <= scale/2, the residual's own scale is <= scale/254: the
pair behaves like ~16-bit fixed precision while costing 2 bytes per
element (vs 4 for fp32 — the >= 1.8x resident/checkpoint/migration
byte cut), and the mass dropped per requant is second-order
(<= scale/508 per element). 'fp8' stores float8_e4m3fn bits BITCAST to
int8 at rest, so serialization and the tensor wire never see an fp8
dtype ("fp8-shaped on CPU via the int8 wire").
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from edl_tpu.ops.pack import (dequantize_int8, quantize_int8,
                              symmetric_scale)

_LANE = 128         # TPU lane width: kernel operands reshape to (-1, 128)
_FORCE_INTERPRET = False

OPTIMIZERS = ("sgdm", "adam")
QUANT_MODES = ("off", "int8", "fp8")


def force_pallas_interpret():
    """Test hook: route the fused update through the Pallas kernels in
    interpret mode on non-TPU backends (equivalence pinning only)."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = True


def _use_pallas() -> bool:
    return _FORCE_INTERPRET or jax.default_backend() == "tpu"


# -- fp8 plane codec (rides the int8 wire) ----------------------------------

FP8_MAX = 448.0     # float8_e4m3fn finite max


def fp8_dtype():
    """float8_e4m3fn if this jax build has it, else None."""
    return getattr(jnp, "float8_e4m3fn", None)


def _fp8_scale(x: jnp.ndarray) -> jnp.ndarray:
    amax = jnp.max(jnp.abs(x))
    return jnp.where(amax > 0, amax / FP8_MAX, 1.0).astype(jnp.float32)


def _quantize_fp8(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    f8 = (x.astype(jnp.float32) / scale).astype(fp8_dtype())
    return jax.lax.bitcast_convert_type(f8, jnp.int8)


def _dequantize_fp8(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    f8 = jax.lax.bitcast_convert_type(q, fp8_dtype())
    return f8.astype(jnp.float32) * scale.astype(jnp.float32)


# -- quantized moment plane --------------------------------------------------


# Adam's SECOND moment always uses the fp8-e4m3 codec (bits still ride
# the int8 wire): v spans many orders of magnitude and sits under a
# sqrt in the update's denominator, so a LINEAR int8 grid zero-floors
# small entries — u = m/(sqrt(0)+eps) then explodes wherever the m
# plane still resolves the entry. An exponent format keeps ~6% relative
# precision across v's whole range; the first moment (gradient-like,
# error-feedback-friendly) stays on the mode's own codec.
V_QUANT = "fp8"


class QPlane(NamedTuple):
    """One moment plane at rest: value payload + error-feedback residual.

    q/rq are int8 (fp8 mode: float8 bits bitcast to int8); scale/rscale
    are fp32 scalars. Serializes as four ordinary array leaves — the
    (q, scale) pairs checkpoints/migration ship at half the fp32 bytes.
    """

    q: jnp.ndarray
    scale: jnp.ndarray
    rq: jnp.ndarray
    rscale: jnp.ndarray


def _dq2(q, scale, rq, rscale, quant: str) -> jnp.ndarray:
    """Reassemble the full-precision moment: payload + residual."""
    if quant == "int8":
        return dequantize_int8(q, scale) + dequantize_int8(rq, rscale)
    return _dequantize_fp8(q, scale) + _dequantize_fp8(rq, rscale)


def _rq2(m: jnp.ndarray, quant: str):
    """Requantize an updated moment; the rounding error becomes the new
    residual (itself quantized — that is what halves the bytes)."""
    if quant == "int8":
        scale = symmetric_scale(m)
        q = quantize_int8(m, scale)
        r = m - dequantize_int8(q, scale)
        rscale = symmetric_scale(r)
        rq = quantize_int8(r, rscale)
    else:
        scale = _fp8_scale(m)
        q = _quantize_fp8(m, scale)
        r = m - _dequantize_fp8(q, scale)
        rscale = _fp8_scale(r)
        rq = _quantize_fp8(r, rscale)
    return q, scale, rq, rscale


def quant_plane(m: jnp.ndarray, quant: str) -> QPlane:
    """Full-precision moment -> resident QPlane."""
    q, scale, rq, rscale = _rq2(m.astype(jnp.float32), quant)
    return QPlane(q=q, scale=scale, rq=rq, rscale=rscale)


def dequant_plane(plane: QPlane, quant: str) -> jnp.ndarray:
    """Resident QPlane -> full-precision moment (payload + residual)."""
    return _dq2(plane.q, plane.scale, plane.rq, plane.rscale, quant)


def zero_plane(n: int, quant: str) -> QPlane:
    """Quantized zero moment (exact: symmetric format round-trips 0)."""
    del quant  # both codecs encode zero as q=0, scale=1
    return QPlane(q=jnp.zeros((n,), jnp.int8),
                  scale=jnp.ones((), jnp.float32),
                  rq=jnp.zeros((n,), jnp.int8),
                  rscale=jnp.ones((), jnp.float32))


# -- optimizer math (the single source of truth for BOTH backends) ----------
# Expression order matters: the momentum-SGD chain is written to be
# bitwise-identical to optax.chain(add_decayed_weights(wd),
# sgd(lr, momentum=mu)) + optax.apply_updates (tests pin it); Adam
# matches optax.adamw's expression order with bias-correction factors
# (c1, c2) precomputed outside and eps_root=0.


def _sgdm_math(p, g, m, lr, mu: float, wd: float):
    if wd:
        g = g + wd * p
    m_new = g + mu * m
    p_new = p + m_new * (-lr)
    return p_new, m_new


def _adam_math(p, g, m, v, lr, c1, c2, b1: float, b2: float,
               eps: float, wd: float):
    # v >= +0.0 exactly on the fp32 path (so the clamp is bitwise-
    # neutral there); a dequantized v can carry a tiny negative
    # residual error, which must not reach the sqrt.
    v = jnp.maximum(v, 0.0)
    m_new = (1 - b1) * g + b1 * m
    v_new = (1 - b2) * (g * g) + b2 * v
    u = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
    if wd:
        u = u + wd * p
    p_new = p + u * (-lr)
    return p_new, m_new, v_new


# -- Pallas kernel bodies ----------------------------------------------------
# Scalars ride as (1, 1) fp32 operands (SMEM-shaped); hyperparameters
# that never change per step (mu, b1, ...) are compile-time statics.


def _sgdm_fp32_kernel(p_ref, g_ref, m_ref, lr_ref, po_ref, mo_ref,
                      *, mu, wd):
    p_new, m_new = _sgdm_math(p_ref[:], g_ref[:], m_ref[:],
                              lr_ref[0, 0], mu, wd)
    po_ref[:] = p_new
    mo_ref[:] = m_new


def _sgdm_q_kernel(p_ref, g_ref, q_ref, s_ref, rq_ref, rs_ref, lr_ref,
                   po_ref, qo_ref, so_ref, rqo_ref, rso_ref,
                   *, mu, wd, quant):
    m = _dq2(q_ref[:], s_ref[0, 0], rq_ref[:], rs_ref[0, 0], quant)
    p_new, m_new = _sgdm_math(p_ref[:], g_ref[:], m, lr_ref[0, 0],
                              mu, wd)
    q, s, rq, rs = _rq2(m_new, quant)
    po_ref[:] = p_new
    qo_ref[:] = q
    so_ref[0, 0] = s
    rqo_ref[:] = rq
    rso_ref[0, 0] = rs


def _adam_fp32_kernel(p_ref, g_ref, m_ref, v_ref, lr_ref, c1_ref,
                      c2_ref, po_ref, mo_ref, vo_ref,
                      *, b1, b2, eps, wd):
    p_new, m_new, v_new = _adam_math(
        p_ref[:], g_ref[:], m_ref[:], v_ref[:], lr_ref[0, 0],
        c1_ref[0, 0], c2_ref[0, 0], b1, b2, eps, wd)
    po_ref[:] = p_new
    mo_ref[:] = m_new
    vo_ref[:] = v_new


def _adam_q_kernel(p_ref, g_ref, qm_ref, sm_ref, rqm_ref, rsm_ref,
                   qv_ref, sv_ref, rqv_ref, rsv_ref, lr_ref, c1_ref,
                   c2_ref, po_ref, qmo_ref, smo_ref, rqmo_ref,
                   rsmo_ref, qvo_ref, svo_ref, rqvo_ref, rsvo_ref,
                   *, b1, b2, eps, wd, quant):
    m = _dq2(qm_ref[:], sm_ref[0, 0], rqm_ref[:], rsm_ref[0, 0], quant)
    v = _dq2(qv_ref[:], sv_ref[0, 0], rqv_ref[:], rsv_ref[0, 0],
             V_QUANT)
    p_new, m_new, v_new = _adam_math(
        p_ref[:], g_ref[:], m, v, lr_ref[0, 0], c1_ref[0, 0],
        c2_ref[0, 0], b1, b2, eps, wd)
    qm, sm, rqm, rsm = _rq2(m_new, quant)
    qv, sv, rqv, rsv = _rq2(v_new, V_QUANT)
    po_ref[:] = p_new
    qmo_ref[:] = qm
    smo_ref[0, 0] = sm
    rqmo_ref[:] = rqm
    rsmo_ref[0, 0] = rsm
    qvo_ref[:] = qv
    svo_ref[0, 0] = sv
    rqvo_ref[:] = rqv
    rsvo_ref[0, 0] = rsv


# -- jitted XLA fallbacks ----------------------------------------------------
# The fallback expressions are jitted so XLA applies the SAME fusion
# (notably fma contraction) whether the bucket update runs standalone
# (the parity gate) or inlined in a jitted train step — eager op-by-op
# execution would differ from the compiled kernel path by an ulp.


@functools.partial(jax.jit, static_argnames=("mu", "wd"))
def _sgdm_xla_fp32(p, g, m, lr, *, mu, wd):
    return _sgdm_math(p, g, m, lr, mu, wd)


@functools.partial(jax.jit, static_argnames=("mu", "wd", "quant"))
def _sgdm_xla_q(p, g, q, s, rq, rs, lr, *, mu, wd, quant):
    m = _dq2(q, s, rq, rs, quant)
    p_new, m_new = _sgdm_math(p, g, m, lr, mu, wd)
    return (p_new,) + _rq2(m_new, quant)


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps", "wd"))
def _adam_xla_fp32(p, g, m, v, lr, c1, c2, *, b1, b2, eps, wd):
    return _adam_math(p, g, m, v, lr, c1, c2, b1, b2, eps, wd)


@functools.partial(jax.jit,
                   static_argnames=("b1", "b2", "eps", "wd", "quant"))
def _adam_xla_q(p, g, qm, sm, rqm, rsm, qv, sv, rqv, rsv, lr, c1, c2,
                *, b1, b2, eps, wd, quant):
    m = _dq2(qm, sm, rqm, rsm, quant)
    v = _dq2(qv, sv, rqv, rsv, V_QUANT)
    p_new, m_new, v_new = _adam_math(p, g, m, v, lr, c1, c2, b1, b2,
                                     eps, wd)
    return (p_new,) + _rq2(m_new, quant) + _rq2(v_new, V_QUANT)


# -- pallas_call wrappers (jitted once per bucket shape) ---------------------


def _shapes(*arrs):
    return tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrs)


_S11 = jax.ShapeDtypeStruct((1, 1), jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("mu", "wd", "interpret"))
def _sgdm_fp32_pallas(p2, g2, m2, lr, *, mu, wd, interpret):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        functools.partial(_sgdm_fp32_kernel, mu=mu, wd=wd),
        out_shape=_shapes(p2, m2),
        interpret=interpret,
    )(p2, g2, m2, lr)


@functools.partial(jax.jit,
                   static_argnames=("mu", "wd", "quant", "interpret"))
def _sgdm_q_pallas(p2, g2, q2, s, rq2, rs, lr, *, mu, wd, quant,
                   interpret):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        functools.partial(_sgdm_q_kernel, mu=mu, wd=wd, quant=quant),
        out_shape=_shapes(p2, q2) + (_S11,) + _shapes(rq2) + (_S11,),
        interpret=interpret,
    )(p2, g2, q2, s, rq2, rs, lr)


@functools.partial(jax.jit,
                   static_argnames=("b1", "b2", "eps", "wd", "interpret"))
def _adam_fp32_pallas(p2, g2, m2, v2, lr, c1, c2, *, b1, b2, eps, wd,
                      interpret):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        functools.partial(_adam_fp32_kernel, b1=b1, b2=b2, eps=eps,
                          wd=wd),
        out_shape=_shapes(p2, m2, v2),
        interpret=interpret,
    )(p2, g2, m2, v2, lr, c1, c2)


@functools.partial(jax.jit,
                   static_argnames=("b1", "b2", "eps", "wd", "quant",
                                    "interpret"))
def _adam_q_pallas(p2, g2, qm2, sm, rqm2, rsm, qv2, sv, rqv2, rsv, lr,
                   c1, c2, *, b1, b2, eps, wd, quant, interpret):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        functools.partial(_adam_q_kernel, b1=b1, b2=b2, eps=eps, wd=wd,
                          quant=quant),
        out_shape=(_shapes(p2, qm2) + (_S11,) + _shapes(rqm2) + (_S11,)
                   + _shapes(qv2) + (_S11,) + _shapes(rqv2) + (_S11,)),
        interpret=interpret,
    )(p2, g2, qm2, sm, rqm2, rsm, qv2, sv, rqv2, rsv, lr, c1, c2)


# -- per-bucket public entry points ------------------------------------------
# p/g are flat fp32 bucket buffers whose length is a multiple of the
# 128-element lane width (plan_buckets(align=128) guarantees it; the
# zero padding is a fixed point of both updates, so it never drifts).


def _lanes(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape(-1, _LANE)


def _s11(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.float32).reshape(1, 1)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def sgdm_bucket(p, g, m_state, lr, *, mu: float, wd: float,
                quant: str = "off"):
    """Fused momentum-SGD update of one bucket.

    m_state: fp32 buffer (quant='off') or :class:`QPlane`. Returns
    (p_new, m_state_new) in the same representation.
    """
    lr = jnp.asarray(lr, jnp.float32)
    if quant == "off":
        if not _use_pallas():
            return _sgdm_xla_fp32(p, g, m_state, lr, mu=mu, wd=wd)
        p2, m2 = _sgdm_fp32_pallas(_lanes(p), _lanes(g),
                                   _lanes(m_state), _s11(lr), mu=mu,
                                   wd=wd, interpret=_interpret())
        return p2.reshape(p.shape), m2.reshape(m_state.shape)
    if not _use_pallas():
        p_new, q, s, rq, rs = _sgdm_xla_q(
            p, g, m_state.q, m_state.scale, m_state.rq,
            m_state.rscale, lr, mu=mu, wd=wd, quant=quant)
        return p_new, QPlane(q=q, scale=s, rq=rq, rscale=rs)
    p2, q2, s, rq2, rs = _sgdm_q_pallas(
        _lanes(p), _lanes(g), _lanes(m_state.q), _s11(m_state.scale),
        _lanes(m_state.rq), _s11(m_state.rscale), _s11(lr), mu=mu,
        wd=wd, quant=quant, interpret=_interpret())
    return p2.reshape(p.shape), QPlane(
        q=q2.reshape(p.shape), scale=s.reshape(()),
        rq=rq2.reshape(p.shape), rscale=rs.reshape(()))


def adam_bucket(p, g, m_state, v_state, lr, c1, c2, *, b1: float,
                b2: float, eps: float, wd: float, quant: str = "off"):
    """Fused Adam(W) update of one bucket.

    c1/c2 are the bias-correction denominators (1 - b^t), precomputed
    by the caller so kernel and XLA paths consume identical scalars.
    Returns (p_new, m_state_new, v_state_new).
    """
    lr = jnp.asarray(lr, jnp.float32)
    c1 = jnp.asarray(c1, jnp.float32)
    c2 = jnp.asarray(c2, jnp.float32)
    if quant == "off":
        if not _use_pallas():
            return _adam_xla_fp32(p, g, m_state, v_state, lr, c1, c2,
                                  b1=b1, b2=b2, eps=eps, wd=wd)
        p2, m2, v2 = _adam_fp32_pallas(
            _lanes(p), _lanes(g), _lanes(m_state), _lanes(v_state),
            _s11(lr), _s11(c1), _s11(c2), b1=b1, b2=b2, eps=eps, wd=wd,
            interpret=_interpret())
        return (p2.reshape(p.shape), m2.reshape(m_state.shape),
                v2.reshape(v_state.shape))
    if not _use_pallas():
        (p_new, qm, sm, rqm, rsm, qv, sv, rqv, rsv) = _adam_xla_q(
            p, g, m_state.q, m_state.scale, m_state.rq,
            m_state.rscale, v_state.q, v_state.scale, v_state.rq,
            v_state.rscale, lr, c1, c2, b1=b1, b2=b2, eps=eps, wd=wd,
            quant=quant)
        return (p_new, QPlane(q=qm, scale=sm, rq=rqm, rscale=rsm),
                QPlane(q=qv, scale=sv, rq=rqv, rscale=rsv))
    (p2, qm2, sm, rqm2, rsm, qv2, sv, rqv2, rsv) = _adam_q_pallas(
        _lanes(p), _lanes(g), _lanes(m_state.q), _s11(m_state.scale),
        _lanes(m_state.rq), _s11(m_state.rscale), _lanes(v_state.q),
        _s11(v_state.scale), _lanes(v_state.rq), _s11(v_state.rscale),
        _s11(lr), _s11(c1), _s11(c2), b1=b1, b2=b2, eps=eps, wd=wd,
        quant=quant, interpret=_interpret())
    mk = QPlane(q=qm2.reshape(p.shape), scale=sm.reshape(()),
                rq=rqm2.reshape(p.shape), rscale=rsm.reshape(()))
    vk = QPlane(q=qv2.reshape(p.shape), scale=sv.reshape(()),
                rq=rqv2.reshape(p.shape), rscale=rsv.reshape(()))
    return p2.reshape(p.shape), mk, vk
