"""int8 gradient-bucket pack/unpack for the compressed DCN leg.

One fused pass over a flat gradient shard: abs-max -> symmetric scale ->
round-to-nearest int8. On TPU this is a single-VMEM-resident Pallas
kernel (the shard is a comm bucket slice, a few MiB — well under the
~16 MiB VMEM bound; the abs-max reduction and the quantized store share
one read of HBM instead of XLA's two). Everywhere else the plain-XLA
expression is used — interpret-mode Pallas is orders of magnitude
slower and this sits in the hot step (same split as
ops/flash_attention.py; `force_pallas_interpret()` is the test hook
that runs the kernel path on CPU to pin equivalence).

The wire format (what `train/comm.py` ships over DCN): int8 payload of
the shard + ONE fp32 scale. Symmetric around zero — no zero-point, so
dequantize is a single multiply and a zero gradient round-trips to
exactly zero. Error feedback upstream (comm._cross_int8) carries the
rounding error, so the format's bias is bounded by scale/2 per element
per step and reclaimed on later steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_QMAX = 127.0
_LANE = 128         # TPU lane width: kernel operands reshape to (-1, 128)
_FORCE_INTERPRET = False


def force_pallas_interpret():
    """Test hook: route pack/unpack through the Pallas kernels in
    interpret mode on non-TPU backends (equivalence pinning only —
    interpret mode is far too slow for the hot step)."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = True


def _use_pallas() -> bool:
    return _FORCE_INTERPRET or jax.default_backend() == "tpu"


# -- shared symmetric-int8 math (single source of truth) ---------------------
# Every int8 quantizer in the tree — this pack/unpack wire, the DGC int8
# value wire (train/dgc.py), and the fused-optimizer moment quantizer
# (ops/opt_kernels.py) — routes through these three expressions, so
# equivalence pinned here holds everywhere. All three are jnp-traceable
# and safe inside Pallas kernel bodies.


def symmetric_scale(x: jnp.ndarray) -> jnp.ndarray:
    """fp32 scale mapping |x|max -> 127; 1.0 for an all-zero input so
    q == 0 and dequantize is exact."""
    amax = jnp.max(jnp.abs(x))
    return jnp.where(amax > 0, amax / _QMAX, 1.0).astype(jnp.float32)


def quantize_int8(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Round-to-nearest symmetric int8 under ``scale`` (no zero-point)."""
    return jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                    -_QMAX, _QMAX).astype(jnp.int8)


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_int8` — one fp32 multiply."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)


# -- plain-XLA reference (the non-TPU hot path) ------------------------------


_scale_of = symmetric_scale  # original internal name (kept for callers)


def _pack_xla(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    scale = symmetric_scale(x)
    return quantize_int8(x, scale), scale


# -- Pallas kernel -----------------------------------------------------------


def _pack_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)
    scale = symmetric_scale(x)
    s_ref[0, 0] = scale
    q_ref[:] = quantize_int8(x, scale)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pack_pallas(x2d: jnp.ndarray, interpret: bool):
    from jax.experimental import pallas as pl

    q, s = pl.pallas_call(
        _pack_kernel,
        out_shape=(jax.ShapeDtypeStruct(x2d.shape, jnp.int8),
                   jax.ShapeDtypeStruct((1, 1), jnp.float32)),
        interpret=interpret,
    )(x2d)
    return q, s[0, 0]


# -- public API --------------------------------------------------------------


def pack_int8(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Flat float shard -> (int8 payload of the same shape, fp32 scale).

    Traceable (used inside jit/shard_map). Kernel path on TPU; the
    ragged tail past a multiple of the 128-lane width is padded with
    zeros for the kernel and sliced back off (zeros never win the
    abs-max, so padding cannot perturb the scale).
    """
    if not _use_pallas():
        return _pack_xla(x)
    n = x.shape[0] if x.ndim == 1 else int(np.prod(x.shape))
    flat = x.reshape(-1)
    pad = (-n) % _LANE
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    q2d, scale = _pack_pallas(flat.reshape(-1, _LANE),
                              interpret=jax.default_backend() != "tpu")
    q = q2d.reshape(-1)[:n].reshape(x.shape)
    return q, scale


def unpack_int8(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int8` (one multiply — no kernel needed;
    XLA fuses it into the consumer)."""
    return dequantize_int8(q, scale)


# -- shared collective wires --------------------------------------------------
# Every cross-chip int8 hop in the tree rides ONE of these two helpers,
# so the allreduce wire (train/comm._cross_int8, train/dgc.sparse_psum)
# and the MoE all-to-all wire (train/comm.moe_all_to_all) encode with
# the same scale/round math and cannot drift: the interpret-mode
# equivalence pin on pack_int8 covers them all. Both are for use INSIDE
# shard_map (they issue lax collectives over a named axis).


def all_gather_int8(x: jnp.ndarray, axis_name: str, *,
                    axis_index_groups=None
                    ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The int8 GATHER wire: pack -> all_gather(q, scale) -> dequantize.

    ``x`` is one chip's flat (1-D) float contribution. Returns
    ``(gathered, local)``: the (group, n) fp32 dequantized
    contributions of every chip in the group, and this chip's own
    dequantized round-trip (what error-feedback callers subtract to
    keep the quantization error local). Wire bytes per chip: n int8
    payload + one fp32 scale.
    """
    from jax import lax
    q, scale = pack_int8(x)
    all_q = lax.all_gather(q, axis_name,
                           axis_index_groups=axis_index_groups)
    all_s = lax.all_gather(scale, axis_name,
                           axis_index_groups=axis_index_groups)
    return (dequantize_int8(all_q, all_s[:, None]),
            dequantize_int8(q, scale))


def all_to_all_int8(x: jnp.ndarray, axis_name: str, *,
                    axis_index_groups=None) -> jnp.ndarray:
    """The int8 ALL-TO-ALL wire: per-destination-block pack ->
    all_to_all(q, scales) -> dequantize.

    ``x`` is destination-major: dim 0 enumerates the group's chips (or
    slices) and block ``x[i]`` is the payload bound for position ``i``
    of the group. Each block gets its OWN symmetric scale (blocks bound
    for different destinations have unrelated magnitudes — one global
    scale would crush the small ones), the int8 payloads and fp32
    scales ride the same all_to_all pattern, and the receiver
    dequantizes source-major blocks. Wire bytes per chip: the off-chip
    payload at 1 byte/element + one fp32 scale per off-chip block. No
    error feedback — activations are transient; callers bound the
    rounding error with a loss-parity gate instead (train/comm's MoE
    dispatch gates).
    """
    from jax import lax
    g = x.shape[0]
    packed = [pack_int8(x[i]) for i in range(g)]  # static unroll:
    # keeps the Pallas kernel path per block on TPU (vmap over a
    # pallas_call would fall back to interpret rules)
    q = jnp.stack([p[0] for p in packed])
    scale = jnp.stack([p[1] for p in packed])
    q_r = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                         tiled=True, axis_index_groups=axis_index_groups)
    s_r = lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0,
                         tiled=True, axis_index_groups=axis_index_groups)
    return dequantize_int8(q_r, s_r.reshape((g,) + (1,) * (x.ndim - 1)))
