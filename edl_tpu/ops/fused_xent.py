"""Streamed-vocab softmax cross-entropy for LM heads.

The last big activation in the LM step is the logits tensor: at
B=16, S=1024, V=32768 it is 2 GB of fp32 that exists only to be
log-softmaxed and gathered. This op never materializes it — the head
matmul and the CE fuse into one pass that streams VOCAB CHUNKS, keeping
a running (max, sum-exp) and the target's logit per row, exactly the
flash-attention trick applied to the classifier axis. The backward
replays the chunks from the saved log-sum-exp: d_logits for a chunk is
(softmax - onehot) — formed chunk-at-a-time and immediately contracted
into d_hidden and that chunk's d_kernel, so the full logits gradient
never exists either. Peak transient memory drops from O(N*V) to
O(N*chunk), which is what lets the LM batch grow past the logits wall.

Plain XLA inside (`lax.fori_loop`/`dynamic_slice` + MXU matmuls with
fp32 accumulation) under a `jax.custom_vjp` — the compiler tiles these
matmuls well; the win here is the memory schedule, not hand-written
vector code.

No reference counterpart (its models are CNNs); net-new tpu-first
capability like ops/flash_attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _chunks(v: int, want: int) -> int:
    """Chunk width: v if it fits, else `want` (the loop handles a ragged
    tail by clamped slices + masking — any vocab keeps the O(N*chunk)
    bound, including primes like GPT-2's 50257)."""
    return v if v <= want else want


def _chunk_cols(ci, chunk, v):
    """(start, global col index grid (1, chunk)) for clamped chunk ci.

    dynamic_slice clamps an out-of-bounds start, so the final ragged
    chunk re-reads some columns of the previous one; the caller masks by
    comparing the global index against the chunk's true [c0, c0+chunk)
    window, which zeroes the overlap exactly once."""
    c0 = ci * chunk
    start = jnp.minimum(c0, v - chunk)
    cols = start + lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
    return c0, start, cols


def _fwd_pass(hidden, kernel, targets, chunk):
    """Returns (lse (N,), target_logit (N,)) streaming vocab chunks."""
    n, d = hidden.shape
    v = kernel.shape[1]
    h32 = hidden.astype(jnp.float32)
    k32 = kernel.astype(jnp.float32)
    n_chunks = -(-v // chunk)

    def body(ci, carry):
        m, l, tgt = carry
        c0, start, cols = _chunk_cols(ci, chunk, v)
        k_blk = lax.dynamic_slice(k32, (0, start), (d, chunk))
        logits = jnp.dot(h32, k_blk,
                         preferred_element_type=jnp.float32)  # (N, C)
        valid = (cols >= c0) & (cols < v)
        logits = jnp.where(valid, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.where(valid, jnp.exp(logits - m_new[:, None]), 0.0),
            axis=-1)
        local = targets - start
        in_chunk = (targets >= c0) & (targets < jnp.minimum(c0 + chunk, v))
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        tgt = jnp.where(in_chunk, picked, tgt)
        return m_new, l, tgt

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    t0 = jnp.zeros((n,), jnp.float32)
    m, l, tgt = lax.fori_loop(0, n_chunks, body, (m0, l0, t0))
    return m + jnp.log(l), tgt


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def streamed_lm_xent(hidden, kernel, targets, chunk: int = 8192):
    """Mean CE of softmax(hidden @ kernel) vs integer targets.

    hidden: (N, d); kernel: (d, V); targets: (N,) int32 in [0, V).
    Equivalent to
    ``-mean(log_softmax(hidden @ kernel)[arange(N), targets])`` without
    ever materializing the (N, V) logits.
    """
    chunk = _chunks(kernel.shape[1], chunk)
    lse, tgt = _fwd_pass(hidden, kernel, targets, chunk)
    return jnp.mean(lse - tgt)


def _xent_fwd(hidden, kernel, targets, chunk):
    chunk = _chunks(kernel.shape[1], chunk)
    lse, tgt = _fwd_pass(hidden, kernel, targets, chunk)
    return jnp.mean(lse - tgt), (hidden, kernel, targets, lse)


def _xent_bwd(chunk, res, g):
    hidden, kernel, targets, lse = res
    n, d = hidden.shape
    v = kernel.shape[1]
    chunk = _chunks(v, chunk)
    h32 = hidden.astype(jnp.float32)
    k32 = kernel.astype(jnp.float32)
    scale = g / n  # d(mean)/d(row)
    n_chunks = -(-v // chunk)

    def body(ci, carry):
        dh, dk = carry
        c0, start, cols = _chunk_cols(ci, chunk, v)
        k_blk = lax.dynamic_slice(k32, (0, start), (d, chunk))
        logits = jnp.dot(h32, k_blk, preferred_element_type=jnp.float32)
        valid = (cols >= c0) & (cols < v)
        p = jnp.where(valid, jnp.exp(logits - lse[:, None]), 0.0)
        local = targets - start
        in_chunk = (targets >= c0) & (targets < jnp.minimum(c0 + chunk, v))
        onehot = (lax.broadcasted_iota(jnp.int32, (1, chunk), 1) ==
                  jnp.clip(local, 0, chunk - 1)[:, None]) & in_chunk[:, None]
        dlogits = (p - onehot.astype(jnp.float32)) * scale
        dh = dh + jnp.dot(dlogits, k_blk.T,
                          preferred_element_type=jnp.float32)
        dk_blk = jnp.dot(h32.T, dlogits,
                         preferred_element_type=jnp.float32)
        # accumulate into the preallocated (d, V) gradient in place —
        # read-add-write is overlap-safe because masked columns
        # contribute exactly 0 from the ragged chunk
        cur = lax.dynamic_slice(dk, (0, start), (d, chunk))
        dk = lax.dynamic_update_slice(dk, cur + dk_blk, (0, start))
        return dh, dk

    dh, dk = lax.fori_loop(
        0, n_chunks, body,
        (jnp.zeros((n, d), jnp.float32), jnp.zeros((d, v), jnp.float32)))
    return (dh.astype(hidden.dtype), dk.astype(kernel.dtype), None)


streamed_lm_xent.defvjp(_xent_fwd, _xent_bwd)
