from edl_tpu.ops.flash_attention import flash_attention
from edl_tpu.ops.fused_xent import streamed_lm_xent

__all__ = ["flash_attention", "streamed_lm_xent"]
