from edl_tpu.ops.augment import (AUGMENT_SEED_KEY, apply_crop,
                                 apply_flip_lr, host_crop_flip_decisions,
                                 make_device_augment, mixup,
                                 normalize_image)
from edl_tpu.ops.flash_attention import flash_attention
from edl_tpu.ops.fused_xent import streamed_lm_xent
from edl_tpu.ops.pack import pack_int8, unpack_int8

__all__ = ["AUGMENT_SEED_KEY", "apply_crop", "apply_flip_lr",
           "flash_attention", "host_crop_flip_decisions",
           "make_device_augment", "mixup", "normalize_image",
           "pack_int8", "streamed_lm_xent", "unpack_int8"]
