"""TCP store server: serves an InMemStore over the framed-JSON protocol.

One thread per connection + a lease-sweeper thread (so TTL expiry generates
DELETE events even with no traffic). CLI:

    python -m edl_tpu.coord.server --port 2379

Capability parity: stands in for the reference's external etcd dependency
(docker/Dockerfile:28-30 bakes etcd into the image; our store is part of the
framework). The C++ daemon in native/store/ is the production flavor; this
Python server is the dev/test flavor — identical protocol and semantics.
"""

from __future__ import annotations

import argparse
import socket
import socketserver
import threading

from edl_tpu.coord import wire
from edl_tpu.coord.store import InMemStore
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.coord.server")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        store: InMemStore = self.server.store  # type: ignore[attr-defined]
        sock: socket.socket = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                req = wire.recv_msg(sock)
            except (wire.WireError, OSError):
                return
            try:
                resp = self._dispatch(store, req)
            except Exception as exc:  # surface the error to the client
                resp = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            try:
                wire.send_msg(sock, resp)
            except OSError:
                return

    @staticmethod
    def _dispatch(store: InMemStore, req: dict) -> dict:
        op = req.get("op")
        if op == "put":
            rev = store.put(req["key"], req["value"], req.get("lease", 0))
            return {"ok": True, "revision": rev}
        if op == "get":
            rec = store.get(req["key"])
            if rec is None:
                return {"ok": True, "record": None}
            return {"ok": True, "record": [rec.key, rec.value, rec.revision, rec.lease]}
        if op == "get_prefix":
            recs, rev = store.get_prefix(req["prefix"])
            return {"ok": True, "revision": rev,
                    "records": [[r.key, r.value, r.revision, r.lease] for r in recs]}
        if op == "delete":
            return {"ok": True, "deleted": store.delete(req["key"])}
        if op == "delete_prefix":
            return {"ok": True, "count": store.delete_prefix(req["prefix"])}
        if op == "put_if_absent":
            won = store.put_if_absent(req["key"], req["value"], req.get("lease", 0))
            return {"ok": True, "won": won}
        if op == "cas":
            won = store.compare_and_swap(
                req["key"], req.get("expect"), req["value"], req.get("lease", 0))
            return {"ok": True, "won": won}
        if op == "lease_grant":
            return {"ok": True, "lease": store.lease_grant(float(req["ttl"]))}
        if op == "lease_keepalive":
            return {"ok": True, "alive": store.lease_keepalive(req["lease"])}
        if op == "lease_revoke":
            return {"ok": True, "revoked": store.lease_revoke(req["lease"])}
        if op == "events_since":
            evs, rev, compacted = store.events_since(
                req["revision"], req.get("prefix", ""))
            return {"ok": True, "revision": rev, "compacted": compacted,
                    "events": [[e.type, e.key, e.value, e.revision] for e in evs]}
        if op == "ping":
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}


class _ThreadingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class StoreServer:
    """In-process handle: start/stop a store server on a port."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 store: InMemStore | None = None, sweep_interval: float = 0.5):
        self.store = store or InMemStore()
        self._server = _ThreadingServer((host, port), _Handler)
        self._server.store = self.store  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._sweep_interval = sweep_interval

    def start(self) -> "StoreServer":
        t = threading.Thread(target=self._server.serve_forever,
                             name="edl-store-serve", daemon=True)
        s = threading.Thread(target=self._sweeper, name="edl-store-sweep",
                             daemon=True)
        t.start()
        s.start()
        self._threads = [t, s]
        log.info("store server listening on :%d", self.port)
        return self

    def _sweeper(self) -> None:
        while not self._stop.wait(self._sweep_interval):
            self.store.sweep()

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def main() -> None:
    parser = argparse.ArgumentParser(description="edl_tpu coordination store")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=2379)
    parser.add_argument("--sweep_interval", type=float, default=0.5)
    args = parser.parse_args()
    server = StoreServer(args.port, args.host, sweep_interval=args.sweep_interval)
    server.start()
    threading.Event().wait()  # serve forever


if __name__ == "__main__":
    main()
