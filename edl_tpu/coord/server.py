"""TCP store server: serves an InMemStore over the framed-JSON protocol.

One thread per connection + a lease-sweeper thread (so TTL expiry generates
DELETE events even with no traffic). CLI:

    python -m edl_tpu.coord.server --port 2379

Capability parity: stands in for the reference's external etcd dependency
(docker/Dockerfile:28-30 bakes etcd into the image; our store is part of the
framework). The C++ daemon in native/store/ is the production flavor; this
Python server is the dev/test flavor — identical protocol and semantics.
"""

from __future__ import annotations

import argparse
import socket
import socketserver
import threading

from edl_tpu.coord import wire
from edl_tpu.coord.store import InMemStore
from edl_tpu.obs import metrics, trace
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.coord.server")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        store: InMemStore = self.server.store  # type: ignore[attr-defined]
        node = getattr(self.server, "node", None)  # replication plane
        sock: socket.socket = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                req = wire.recv_msg(sock)
            except (wire.WireError, OSError):
                return
            # Trace seam: a request sent under an active span carries
            # its context ("_tc", popped here so replication forwarding
            # never re-ships it); the op then executes as a child span
            # of the caller's — the store hop of a resize trace.
            ctx = trace.extract(req)
            resp = None
            if node is not None:
                # The replica node owns routing: shard REDIRECTs,
                # follower NOT_LEADER refusals, peer replication ops and
                # leader commit-waits all happen here. None means "serve
                # from the local store as usual" (reads, watches, and
                # everything on a standalone server).
                try:
                    resp = node.intercept(req)
                except Exception as exc:  # noqa: BLE001 — surface it
                    resp = {"ok": False,
                            "error": f"{type(exc).__name__}: {exc}"}
            if resp is None and req.get("op") == "watch":
                # long-lived: the connection becomes a push stream and
                # ends when the client disconnects or the server stops
                self._serve_watch(store, sock, req, self.server)
                return
            if resp is None:
                try:
                    if ctx is not None:
                        with trace.span(f"store.{req.get('op')}",
                                        parent=ctx):
                            resp = self._dispatch(store, req)
                    else:
                        resp = self._dispatch(store, req)
                except Exception as exc:  # surface the error to the client
                    resp = {"ok": False,
                            "error": f"{type(exc).__name__}: {exc}"}
            try:
                wire.send_msg(sock, resp)
            except OSError:
                return

    @staticmethod
    def _serve_watch(store: InMemStore, sock: socket.socket,
                     req: dict, server) -> None:
        """The server half of the watch stream (wire.py protocol doc):
        ack, then event frames as they happen, with empty heartbeat
        frames advancing the client's resume anchor while idle — the
        heartbeat is also how a dead client is detected (its send
        fails) so the watcher never leaks."""
        try:
            heartbeat = float(req.get("heartbeat") or 2.0)
            watch = store.watch(req.get("prefix", ""),
                                start_revision=req.get("start_revision"))
        except Exception as exc:  # noqa: BLE001 — surface to the client
            try:
                wire.send_msg(sock, {"ok": False,
                                     "error": f"{type(exc).__name__}: {exc}"})
            except OSError:
                pass
            return
        # registered so StoreServer.stop() can close live streams: a
        # stopped server whose handler threads kept streaming would look
        # alive to clients and mask a restart (resume/compaction would
        # never trigger)
        with server.watch_lock:
            server.active_watches.add(watch)
        try:
            wire.send_msg(sock, {"ok": True, "watching": True,
                                 "revision": watch.created_revision})
            while True:
                batch = watch.get(timeout=heartbeat)
                if batch is None:
                    if watch.cancelled:
                        return
                    rev = watch.progress_revision()
                    if rev is None:
                        continue  # an event raced in: deliver it next loop
                    msg = {"ok": True, "events": [], "revision": rev,
                           "compacted": False}
                else:
                    # merge whatever else is already queued into this
                    # frame (up to the wire ceiling): under a burst the
                    # stream ships a few big frames instead of thousands
                    # of one-event ones. A compacted batch is never
                    # merged — it is a resync signal, not events — so it
                    # ships alone right after.
                    events = list(batch.events)
                    revision = batch.revision
                    tail = None
                    while not batch.compacted \
                            and len(events) < wire.MAX_EVENTS_PER_FRAME:
                        nxt = watch.get(timeout=0)
                        if nxt is None:
                            break
                        if nxt.compacted:
                            tail = nxt
                            break
                        events.extend(nxt.events)
                        revision = nxt.revision
                    msg = {"ok": True,
                           "events": [[e.type, e.key, e.value, e.revision]
                                      for e in events],
                           "revision": revision,
                           "compacted": batch.compacted}
                    if tail is not None:
                        wire.send_msg(sock, msg)
                        msg = {"ok": True, "events": [],
                               "revision": tail.revision, "compacted": True}
                wire.send_msg(sock, msg)
        except OSError:
            return
        finally:
            with server.watch_lock:
                server.active_watches.discard(watch)
            watch.cancel()

    @staticmethod
    def _dispatch(store: InMemStore, req: dict) -> dict:
        op = req.get("op")
        if op == "put":
            rev = store.put(req["key"], req["value"], req.get("lease", 0))
            return {"ok": True, "revision": rev}
        if op == "get":
            rec = store.get(req["key"])
            if rec is None:
                return {"ok": True, "record": None}
            return {"ok": True, "record": [rec.key, rec.value, rec.revision, rec.lease]}
        if op == "get_prefix":
            recs, rev = store.get_prefix(req["prefix"])
            return {"ok": True, "revision": rev,
                    "records": [[r.key, r.value, r.revision, r.lease] for r in recs]}
        if op == "delete":
            return {"ok": True, "deleted": store.delete(req["key"])}
        if op == "delete_prefix":
            return {"ok": True, "count": store.delete_prefix(req["prefix"])}
        if op == "put_if_absent":
            won = store.put_if_absent(req["key"], req["value"], req.get("lease", 0))
            return {"ok": True, "won": won}
        if op == "cas":
            won = store.compare_and_swap(
                req["key"], req.get("expect"), req["value"], req.get("lease", 0))
            return {"ok": True, "won": won}
        if op == "lease_grant":
            return {"ok": True, "lease": store.lease_grant(float(req["ttl"]))}
        if op == "lease_keepalive":
            return {"ok": True, "alive": store.lease_keepalive(req["lease"])}
        if op == "lease_revoke":
            return {"ok": True, "revoked": store.lease_revoke(req["lease"])}
        if op == "events_since":
            evs, rev, compacted = store.events_since(
                req["revision"], req.get("prefix", ""))
            return {"ok": True, "revision": rev, "compacted": compacted,
                    "events": [[e.type, e.key, e.value, e.revision] for e in evs]}
        if op == "ping":
            return {"ok": True}
        if op == "status":
            # replicated nodes intercept this with role/term/leader
            # detail; a standalone server answers enough for a client's
            # leader probe to conclude "just use me"
            return {"ok": True, "role": "standalone", "leader": None,
                    "term": 0, "revision": store.current_revision}
        return {"ok": False, "error": f"unknown op {op!r}"}


class _ThreadingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class StoreServer:
    """In-process handle: start/stop a store server on a port."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 store: InMemStore | None = None, sweep_interval: float = 0.5,
                 node=None):
        self.store = store or InMemStore()
        self.node = node  # replication plane (coord/replication.py) or None
        self._server = _ThreadingServer((host, port), _Handler)
        self._server.store = self.store  # type: ignore[attr-defined]
        self._server.node = node  # type: ignore[attr-defined]
        self._server.active_watches = set()  # type: ignore[attr-defined]
        self._server.watch_lock = threading.Lock()  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._sweep_interval = sweep_interval
        # the dict API stays the engine's contract; the registry is the
        # scrape view over it (unregistered on stop)
        self._obs = metrics.register_stats("store", self.store.stats)

    def start(self) -> "StoreServer":
        t = threading.Thread(target=self._server.serve_forever,
                             name="edl-store-serve", daemon=True)
        s = threading.Thread(target=self._sweeper, name="edl-store-sweep",
                             daemon=True)
        t.start()
        s.start()
        self._threads = [t, s]
        log.info("store server listening on :%d", self.port)
        return self

    def _sweeper(self) -> None:
        while not self._stop.wait(self._sweep_interval):
            self.store.sweep()
            if self.node is not None:
                # the election sidecar store must keep expiring leases
                # even while the data store is a passive follower
                self.node.sweep()

    def stop(self) -> None:
        self._stop.set()
        # end live watch streams: their handler threads wake, close the
        # connections, and clients reconnect (resuming by revision)
        with self._server.watch_lock:  # type: ignore[attr-defined]
            watches = list(self._server.active_watches)  # type: ignore[attr-defined]
        for watch in watches:
            watch.cancel()
        self._server.shutdown()
        self._server.server_close()
        metrics.unregister(self._obs)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def main() -> None:
    parser = argparse.ArgumentParser(description="edl_tpu coordination store")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=2379)
    parser.add_argument("--sweep_interval", type=float, default=0.5)
    args = parser.parse_args()
    server = StoreServer(args.port, args.host, sweep_interval=args.sweep_interval)
    server.start()
    threading.Event().wait()  # serve forever


if __name__ == "__main__":
    main()
