"""Blocking store client speaking the framed-JSON protocol.

Implements the same ``Store`` API as ``InMemStore`` so registry/launcher code
is backend-agnostic (in-process for tests, TCP for real jobs — the pattern the
reference gets from swapping etcd/in-mem stores, pkg/master/inmem_store.go).

Reconnect-on-error with bounded retries mirrors the reference's etcd wrapper
decorator (discovery/etcd_client.py:40-49).
"""

from __future__ import annotations

import socket
import threading
import time

from edl_tpu.coord import wire
from edl_tpu.coord.store import Event, Record, Store
from edl_tpu.utils import exceptions
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.logging import get_logger
from edl_tpu.utils.net import split_endpoint

log = get_logger("edl_tpu.coord.client")


def _typed_error(message: str) -> EdlStoreError:
    """Re-hydrate server-side typed errors: the server serializes them as
    '<TypeName>: <msg>' (coord/server.py), and callers distinguish e.g.
    EdlLeaseExpired from generic store failures — the subtype must survive
    the wire, not only in-process stores."""
    name, _, rest = message.partition(":")
    cls = getattr(exceptions, name.strip(), None)
    if isinstance(cls, type) and issubclass(cls, EdlStoreError):
        return cls(rest.strip() or message)
    return EdlStoreError(message)


class StoreClient(Store):
    def __init__(self, endpoint: str, timeout: float = 5.0,
                 connect_retries: int = 30, retry_interval: float = 0.3):
        self._endpoint = endpoint
        self._timeout = timeout
        self._connect_retries = connect_retries
        self._retry_interval = retry_interval
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    # -- connection management --------------------------------------------

    def _connect(self) -> socket.socket:
        host, port = split_endpoint(self._endpoint)
        last: Exception | None = None
        for _ in range(self._connect_retries):
            try:
                sock = socket.create_connection((host, port), timeout=self._timeout)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except OSError as exc:
                last = exc
                time.sleep(self._retry_interval)
        raise EdlStoreError(f"cannot connect to store at {self._endpoint}: {last}")

    # Ops safe to re-send after a connection error. Mutating-but-idempotent
    # ops (put/delete) are included: re-applying them yields the same state.
    # put_if_absent / cas are NOT: the first send may have been applied with
    # the response lost, and a blind resend would report the wrong outcome
    # (e.g. a rank claim that succeeded looking lost). Those surface an
    # EdlStoreError and the caller decides (e.g. read back ownership).
    _RETRYABLE = frozenset({
        "get", "get_prefix", "events_since", "ping", "lease_keepalive",
        "put", "delete", "delete_prefix", "lease_revoke", "lease_grant",
    })

    def _call(self, **req) -> dict:
        retryable = req.get("op") in self._RETRYABLE
        with self._lock:
            attempts = 2 if retryable else 1
            for attempt in range(1, attempts + 1):
                if self._sock is None:
                    self._sock = self._connect()
                try:
                    wire.send_msg(self._sock, req)
                    resp = wire.recv_msg(self._sock)
                    break
                except (OSError, wire.WireError) as exc:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    if attempt == attempts:
                        raise EdlStoreError(
                            f"store rpc {req.get('op')} failed: {exc}") from exc
            if not resp.get("ok"):
                raise _typed_error(resp.get("error", "unknown store error"))
            return resp

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    # -- Store API ---------------------------------------------------------

    def put(self, key: str, value: str, lease: int = 0) -> int:
        return self._call(op="put", key=key, value=value, lease=lease)["revision"]

    def get(self, key: str) -> Record | None:
        rec = self._call(op="get", key=key)["record"]
        return None if rec is None else Record(*rec)

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        resp = self._call(op="get_prefix", prefix=prefix)
        return [Record(*r) for r in resp["records"]], resp["revision"]

    def delete(self, key: str) -> bool:
        return self._call(op="delete", key=key)["deleted"]

    def delete_prefix(self, prefix: str) -> int:
        return self._call(op="delete_prefix", prefix=prefix)["count"]

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        return self._call(op="put_if_absent", key=key, value=value, lease=lease)["won"]

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        return self._call(op="cas", key=key, expect=expect, value=value,
                          lease=lease)["won"]

    def lease_grant(self, ttl: float) -> int:
        return self._call(op="lease_grant", ttl=ttl)["lease"]

    def lease_keepalive(self, lease: int) -> bool:
        return self._call(op="lease_keepalive", lease=lease)["alive"]

    def lease_revoke(self, lease: int) -> bool:
        return self._call(op="lease_revoke", lease=lease)["revoked"]

    def events_since(self, revision: int, prefix: str = ""
                     ) -> tuple[list[Event], int, bool]:
        resp = self._call(op="events_since", revision=revision, prefix=prefix)
        return ([Event(*e) for e in resp["events"]], resp["revision"],
                resp["compacted"])

    def ping(self) -> bool:
        try:
            self._call(op="ping")
            return True
        except EdlStoreError:
            return False


class LeaseKeeper:
    """Background thread refreshing a lease (reference utils/register.py's
    1s refresher thread; discovery/register.py:41-77 retry/re-register loop
    lives in ServiceRegistry on top of this)."""

    def __init__(self, store: Store, lease: int, interval: float,
                 on_lost=None):
        self.store = store
        self.lease = lease
        self.interval = interval
        self.on_lost = on_lost
        self.lost = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"lease-keeper-{lease}")

    def start(self) -> "LeaseKeeper":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                alive = self.store.lease_keepalive(self.lease)
            except EdlStoreError as exc:
                log.warning("lease %d keepalive error: %s", self.lease, exc)
                continue
            if not alive:
                log.error("lease %d lost", self.lease)
                self.lost.set()
                if self.on_lost is not None:
                    self.on_lost()
                return

    def stop(self, revoke: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        if revoke and not self.lost.is_set():
            try:
                self.store.lease_revoke(self.lease)
            except EdlStoreError:
                pass
