"""Blocking store client speaking the framed-JSON protocol.

Implements the same ``Store`` API as ``InMemStore`` so registry/launcher code
is backend-agnostic (in-process for tests, TCP for real jobs — the pattern the
reference gets from swapping etcd/in-mem stores, pkg/master/inmem_store.go).

Reconnect-on-error with bounded retries mirrors the reference's etcd wrapper
decorator (discovery/etcd_client.py:40-49).

r17 (replicated store): the ``endpoint`` argument accepts a
comma-joined replica list ("h0:p,h1:p,h2:p"). The client talks to one
endpoint at a time and fails over transparently: transport errors
rotate to the next replica under the shared jittered-exponential
``Backoff`` (utils/backoff.py — the same schedule the watch reconnect
uses, so a leader kill does not produce a synchronized retry herd), a
``not_leader`` refusal re-targets the named leader (or rotates until
the new leader emerges from election), and a shard ``redirect`` refusal
follows the owning group's endpoints. Refusals are safe for ALL ops
including put_if_absent/cas — a refusing server did not apply the op —
while transport errors keep the old ambiguity rules. Hinted hops are
bounded (``EDL_TPU_STORE_REDIRECT_HOPS``) so a misconfigured topology
surfaces as a clear "redirect loop" error instead of a hang.
"""

from __future__ import annotations

import socket
import threading
import time

from collections import deque

from edl_tpu.coord import wire
from edl_tpu.coord.store import Event, Record, Store, Watch, WatchBatch
from edl_tpu.obs import metrics
from edl_tpu.obs import recorder as flight
from edl_tpu.utils import config, exceptions
from edl_tpu.utils.backoff import Backoff
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.logging import get_logger
from edl_tpu.utils.net import split_endpoint

log = get_logger("edl_tpu.coord.client")


def _typed_error(message: str) -> EdlStoreError:
    """Re-hydrate server-side typed errors: the server serializes them as
    '<TypeName>: <msg>' (coord/server.py), and callers distinguish e.g.
    EdlLeaseExpired from generic store failures — the subtype must survive
    the wire, not only in-process stores."""
    name, _, rest = message.partition(":")
    cls = getattr(exceptions, name.strip(), None)
    if isinstance(cls, type) and issubclass(cls, EdlStoreError):
        return cls(rest.strip() or message)
    return EdlStoreError(message)


class StoreClient(Store):
    def __init__(self, endpoint: str, timeout: float = 5.0,
                 connect_retries: int = 30, retry_interval: float = 0.3,
                 max_hops: int | None = None):
        eps = [e for e in (p.strip() for p in endpoint.split(",")) if e]
        if not eps:
            raise EdlStoreError("empty store endpoint list")
        self._endpoint = ",".join(eps)  # display / compat
        self._timeout = timeout
        self._connect_retries = connect_retries
        self._retry_interval = retry_interval
        self._max_hops = max_hops if max_hops is not None \
            else max(1, config.env_int("EDL_TPU_STORE_REDIRECT_HOPS", 4))
        self._backoff_base = config.env_float(
            "EDL_TPU_STORE_FAILOVER_BACKOFF", retry_interval)
        # endpoint-order state has its own small lock so the watch
        # reader thread can pick a dial target while a request holds
        # the main op lock
        self._ep_lock = threading.Lock()
        self._endpoints = eps          # guarded-by: _ep_lock
        self._cursor = 0               # guarded-by: _ep_lock
        self._preferred: str | None = None  # guarded-by: _ep_lock
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None  # guarded-by: _lock

    # -- connection management --------------------------------------------

    def _candidates(self) -> list[str]:
        """Dial order: the leader hint first, then the replica list
        rotated so the most recently working endpoint leads."""
        with self._ep_lock:
            eps = self._endpoints[self._cursor:] \
                + self._endpoints[:self._cursor]
            if self._preferred is not None:
                eps = [self._preferred] + [e for e in eps
                                           if e != self._preferred]
            return eps

    def _note_connected(self, endpoint: str) -> None:
        with self._ep_lock:
            if endpoint in self._endpoints:
                self._cursor = self._endpoints.index(endpoint)

    def _set_preferred(self, endpoint: str) -> None:
        """Leader hint from a not_leader refusal; unknown endpoints are
        learned (the hint may name a replica added after this client
        was configured)."""
        with self._ep_lock:
            if endpoint not in self._endpoints:
                self._endpoints.append(endpoint)
            self._preferred = endpoint

    def _rotate(self) -> None:
        with self._ep_lock:
            self._preferred = None
            self._cursor = (self._cursor + 1) % len(self._endpoints)

    def _retarget(self, endpoints: list[str]) -> None:
        """Shard REDIRECT: this client now talks to the owning group."""
        eps = [e for e in endpoints if e]
        if not eps:
            return
        with self._ep_lock:
            self._endpoints = eps
            self._cursor = 0
            self._preferred = None

    def _connect_once(self) -> socket.socket:
        """ONE pass over the candidate endpoints, no internal retry
        loop. Callers that own a reconnect cadence (ClientWatch's
        growing jittered backoff) use this so a dead server is dialed
        once per backoff step — not ``connect_retries`` rounds per step,
        which is the thundering herd the relay tier exists to absorb."""
        last: Exception | None = None
        for ep in self._candidates():
            try:
                sock = socket.create_connection(
                    split_endpoint(ep), timeout=self._timeout)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._note_connected(ep)
                return sock
            except OSError as exc:
                last = exc
                with self._ep_lock:
                    if self._preferred == ep:
                        # a leader hint that does not even accept a
                        # connection is stale — stop chasing it
                        self._preferred = None
        raise EdlStoreError(
            f"cannot connect to store at {self._endpoint}: {last}")

    def _connect(self) -> socket.socket:
        last: EdlStoreError | None = None
        backoff = Backoff(base=self._retry_interval,
                          max_delay=self._retry_interval * 2)
        for _ in range(self._connect_retries):
            try:
                return self._connect_once()
            except EdlStoreError as exc:
                last = exc
            backoff.sleep()
        raise last if last is not None else EdlStoreError(
            f"cannot connect to store at {self._endpoint}")

    # Ops safe to re-send after a connection error. Mutating-but-idempotent
    # ops (put/delete) are included: re-applying them yields the same state.
    # put_if_absent / cas are NOT: the first send may have been applied with
    # the response lost, and a blind resend would report the wrong outcome
    # (e.g. a rank claim that succeeded looking lost). Those surface an
    # EdlStoreError and the caller decides (e.g. read back ownership).
    # (Structured REFUSALS — not_leader / redirect — are different: the
    # server answered without applying, so every op may re-route.)
    _RETRYABLE = frozenset({
        "get", "get_prefix", "events_since", "ping", "lease_keepalive",
        "put", "delete", "delete_prefix", "lease_revoke", "lease_grant",
    })

    def _call(self, **req) -> dict:
        retryable = req.get("op") in self._RETRYABLE
        with self._lock:
            transport_errors = 0
            hinted_hops = 0
            blind_rounds = 0
            last_hint: str | None = None
            failover = Backoff(base=self._backoff_base,
                               max_delay=max(1.0, self._backoff_base * 8))
            while True:
                if self._sock is None:
                    self._sock = self._connect()
                try:
                    wire.send_msg(self._sock, req)
                    resp = wire.recv_msg(self._sock)
                except (OSError, wire.WireError) as exc:
                    self._drop_sock()
                    transport_errors += 1
                    if transport_errors >= (2 if retryable else 1):
                        raise EdlStoreError(
                            f"store rpc {req.get('op')} failed: {exc}"
                        ) from exc
                    self._rotate()
                    continue
                if resp.get("ok"):
                    return resp
                if resp.get("redirect"):
                    # shard refusal: definitively not applied — follow
                    # the owner group, bounded (a loop here means the
                    # servers disagree about the topology, not a
                    # transient to wait out)
                    self._drop_sock()
                    hinted_hops += 1
                    if hinted_hops > self._max_hops:
                        raise EdlStoreError(
                            f"store rpc {req.get('op')}: redirect loop "
                            f"({hinted_hops} hops ending at "
                            f"{self._endpoint}) — shard topology "
                            "disagrees between servers; check "
                            "EDL_TPU_STORE_ENDPOINTS groups")
                    self._retarget(resp.get("endpoints") or ())
                    continue
                if resp.get("not_leader"):
                    # leadership refusal: not applied. A FRESH hint is
                    # followed immediately; a repeated/absent hint means
                    # failover is in flight — rotate + jittered backoff
                    # until the new leader emerges (bounded like the
                    # connect budget, so "no quorum" is an error, not a
                    # hang).
                    self._drop_sock()
                    blind_rounds += 1
                    # flight-recorder trail: every client-visible
                    # leadership bounce, with the hint that drove it
                    flight.record("store_failover", op=req.get("op"),
                                  hint=resp.get("leader"),
                                  round=blind_rounds)
                    if blind_rounds > self._connect_retries:
                        raise EdlStoreError(
                            f"store rpc {req.get('op')}: no leader "
                            f"emerged among {self._endpoint}")
                    hint = resp.get("leader")
                    if hint and hint != last_hint:
                        last_hint = hint
                        self._set_preferred(hint)
                        continue
                    self._rotate()
                    failover.sleep()
                    continue
                raise _typed_error(resp.get("error", "unknown store error"))

    def _drop_sock(self) -> None:  # holds-lock: _lock
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop_sock()

    # -- Store API ---------------------------------------------------------

    def put(self, key: str, value: str, lease: int = 0) -> int:
        return self._call(op="put", key=key, value=value, lease=lease)["revision"]

    def get(self, key: str) -> Record | None:
        rec = self._call(op="get", key=key)["record"]
        return None if rec is None else Record(*rec)

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        resp = self._call(op="get_prefix", prefix=prefix)
        return [Record(*r) for r in resp["records"]], resp["revision"]

    def delete(self, key: str) -> bool:
        return self._call(op="delete", key=key)["deleted"]

    def delete_prefix(self, prefix: str) -> int:
        return self._call(op="delete_prefix", prefix=prefix)["count"]

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        return self._call(op="put_if_absent", key=key, value=value, lease=lease)["won"]

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        return self._call(op="cas", key=key, expect=expect, value=value,
                          lease=lease)["won"]

    def lease_grant(self, ttl: float) -> int:
        return self._call(op="lease_grant", ttl=ttl)["lease"]

    def lease_keepalive(self, lease: int) -> bool:
        return self._call(op="lease_keepalive", lease=lease)["alive"]

    def lease_revoke(self, lease: int) -> bool:
        return self._call(op="lease_revoke", lease=lease)["revoked"]

    def events_since(self, revision: int, prefix: str = ""
                     ) -> tuple[list[Event], int, bool]:
        resp = self._call(op="events_since", revision=revision, prefix=prefix)
        return ([Event(*e) for e in resp["events"]], resp["revision"],
                resp["compacted"])

    def watch(self, prefix: str = "", start_revision: int | None = None,
              heartbeat: float = 2.0, via_relay: bool = True,
              on_resume=None) -> "ClientWatch":
        """Long-lived watch stream on its own connection (the main
        socket stays strict request/response). Reconnects on any error
        and resumes from the last delivered revision, so events are
        delivered exactly once across server restarts — unless the
        server compacted past the resume point, in which case the
        consumer receives an explicit ``compacted`` batch.

        With ``EDL_TPU_RELAY_ENDPOINTS`` set, watch streams dial the
        relay tier instead of the store (same protocol, same resume
        contract — coord/relay.py) so a fleet of watchers costs the
        store one upstream stream per distinct prefix. ``via_relay=
        False`` forces a direct stream — the relay itself uses it for
        its upstream (never watch through yourself)."""
        if via_relay:
            relay_eps = config.env_str("EDL_TPU_RELAY_ENDPOINTS", "")
            if relay_eps:
                return self._relay_client(relay_eps).watch(
                    prefix, start_revision, heartbeat=heartbeat,
                    via_relay=False, on_resume=on_resume)
        return ClientWatch(self, prefix, start_revision,
                           heartbeat=heartbeat, on_resume=on_resume)

    def _relay_client(self, endpoints: str) -> "StoreClient":
        """Lazily-built sibling client aimed at the relay tier (watch
        streams only; everything else keeps talking to the store)."""
        with self._ep_lock:
            cached = getattr(self, "_relay", None)
            if cached is not None and cached._endpoint == \
                    ",".join(e for e in (p.strip()
                                         for p in endpoints.split(","))
                             if e):
                return cached
        relay = StoreClient(endpoints, timeout=self._timeout,
                            connect_retries=self._connect_retries,
                            retry_interval=self._retry_interval,
                            max_hops=self._max_hops)
        with self._ep_lock:
            self._relay = relay
        return relay

    def ping(self) -> bool:
        try:
            self._call(op="ping")
            return True
        except EdlStoreError:
            return False

    def status(self) -> dict:
        """Role/term/leader/revision of the endpoint currently talked
        to (leader discovery + the bench's failover probes)."""
        return self._call(op="status")


class ClientWatch(Watch):
    """Client half of a watch stream: dedicated socket + reader thread.

    The reader pushes event/compacted batches into a local queue
    (heartbeat frames only advance the resume anchor). On any transport
    error it reconnects and re-subscribes from ``last seen revision``,
    which the server replays from its bounded event history — exactly
    once unless compacted, which is surfaced as a compacted batch. A
    reconnect therefore never silently loses or duplicates events.
    """

    def __init__(self, client: "StoreClient", prefix: str,
                 start_revision: int | None, *, heartbeat: float = 2.0,
                 reconnect_backoff: float = 0.2, on_resume=None):
        self._client = client
        self.prefix = prefix
        self._heartbeat = heartbeat
        # called with the resume revision after every successful
        # RE-subscribe (not the first ack) — the relay uses it to leave
        # a relay_resume trail in the flight recorder
        self._on_resume = on_resume
        # shared jittered-exponential schedule (utils/backoff.py): a
        # fleet of watchers re-attaching after a leader kill must not
        # re-dial in lockstep
        self._backoff = Backoff(base=reconnect_backoff,
                                max_delay=max(1.0, reconnect_backoff * 10))
        self._last_rev = start_revision  # None until the first ack
        self.created_revision = start_revision or 0
        self._cond = threading.Condition()
        self._queue: deque[WatchBatch] = deque()  # guarded-by: _cond
        self._stop = threading.Event()
        self._sock: socket.socket | None = None   # guarded-by: _cond
        self._ready = threading.Event()   # first ack received
        self._rejected: str | None = None  # server refused the op
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"store-watch-{prefix}")
        self._thread.start()
        # Block until the subscription is established so "events after
        # watch() returned" is a real guarantee, not a race. A server
        # that REJECTS the op (no watch support) raises — try_watch
        # falls back to polling; a merely unreachable server keeps
        # retrying in the background instead.
        self._ready.wait(timeout=client._timeout)
        if self._rejected is not None:
            self.cancel()
            raise EdlStoreError(f"watch rejected: {self._rejected}")

    # -- reader thread -------------------------------------------------------

    def _run(self) -> None:
        first = True
        redirect_hops = 0
        while not self._stop.is_set():
            try:
                # _connect_once, not _connect: the jittered backoff at
                # the bottom of THIS loop owns the retry cadence — the
                # old path re-dialed a dead follower connect_retries
                # times per reconnect attempt in a near-tight loop
                sock = self._client._connect_once()
            except EdlStoreError:
                if self._backoff.sleep(self._stop):
                    return
                continue
            with self._cond:
                if self._stop.is_set():
                    sock.close()
                    return
                self._sock = sock
            try:
                wire.send_msg(sock, {"op": "watch", "prefix": self.prefix,
                                     "start_revision": self._last_rev,
                                     "heartbeat": self._heartbeat})
                # heartbeats bound the silence: a server that stops
                # sending for several heartbeat periods is dead
                sock.settimeout(max(1.0, self._heartbeat * 5))
                ack = wire.recv_msg(sock)
                if not (ack.get("ok") and ack.get("watching")):
                    if ack.get("redirect") or ack.get("not_leader"):
                        # routing refusal, not "op unsupported": follow
                        # the shard owner / another replica — bounded,
                        # so disagreeing servers surface as rejection
                        redirect_hops += 1
                        if redirect_hops <= self._client._max_hops:
                            if ack.get("redirect") and ack.get("endpoints"):
                                self._client._retarget(ack["endpoints"])
                            else:
                                self._client._rotate()
                            continue
                    # an explicit refusal is permanent (op unsupported):
                    # surface it instead of reconnect-looping forever
                    self._rejected = str(ack.get("error", ack))
                    self._ready.set()
                    return
                redirect_hops = 0
                self._backoff.reset()
                if self._last_rev is None:
                    self._last_rev = int(ack["revision"])
                    self.created_revision = self._last_rev
                self._ready.set()
                if not first:
                    log.info("watch %r resumed from revision %d",
                             self.prefix, self._last_rev)
                    if self._on_resume is not None:
                        try:
                            self._on_resume(self._last_rev)
                        except Exception:  # noqa: BLE001 — observer only
                            log.exception("watch on_resume callback failed")
                first = False
                while True:
                    msg = wire.recv_msg(sock)
                    events = tuple(Event(*e) for e in msg.get("events", ()))
                    revision = int(msg["revision"])
                    compacted = bool(msg.get("compacted"))
                    self._last_rev = revision
                    if events or compacted:
                        self._push(WatchBatch(events, revision, compacted))
            except (OSError, wire.WireError, KeyError, TypeError,
                    ValueError) as exc:
                if not self._stop.is_set():
                    log.debug("watch %r stream error (%s); reconnecting",
                              self.prefix, exc)
            finally:
                with self._cond:
                    self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass
            if self._backoff.sleep(self._stop):
                return

    def _push(self, batch: WatchBatch) -> None:
        with self._cond:
            self._queue.append(batch)
            self._cond.notify_all()

    # -- consumer side -------------------------------------------------------

    def get(self, timeout: float | None = None) -> WatchBatch | None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._stop.is_set():
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._queue:
                return self._queue.popleft()
            return None

    def progress_revision(self) -> int | None:
        with self._cond:
            if self._queue:
                return None
            return self._last_rev

    def cancel(self) -> None:
        self._stop.set()
        with self._cond:
            sock = self._sock
            self._sock = None
            self._cond.notify_all()
        if sock is not None:
            try:
                sock.close()  # wakes the blocked recv
            except OSError:
                pass
        self._thread.join(timeout=2.0)

    @property
    def cancelled(self) -> bool:
        return self._stop.is_set()


class LeaseKeeper:
    """Background thread refreshing a lease (reference utils/register.py's
    1s refresher thread; discovery/register.py:41-77 retry/re-register loop
    lives in ServiceRegistry on top of this)."""

    def __init__(self, store: Store, lease: int, interval: float,
                 on_lost=None):
        self.store = store
        self.lease = lease
        self.interval = interval
        self.on_lost = on_lost
        self.lost = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"lease-keeper-{lease}")

    def start(self) -> "LeaseKeeper":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                alive = self.store.lease_keepalive(self.lease)
            except EdlStoreError as exc:
                log.warning("lease %d keepalive error: %s", self.lease, exc)
                continue
            if not alive:
                log.error("lease %d lost", self.lease)
                self.lost.set()
                if self.on_lost is not None:
                    self.on_lost()
                return

    def stop(self, revoke: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        if revoke and not self.lost.is_set():
            try:
                self.store.lease_revoke(self.lease)
            except EdlStoreError:
                pass


class HostLeaseCoalescer:
    """One host-scoped lease carrying ALL of the host's pod
    registrations, refreshed by a single keepalive write per interval.

    40 pods per host means 40x fewer keepalive writes hitting the
    leader than per-pod leases — the multiplier the 100k-pod control
    plane needs (doc/design_coord.md). The TTL contract is unchanged:
    each keepalive re-arms deadline = now + ttl, never further, so
    coalescing reduces WRITE volume, not failure-detection latency.
    If the host lease expires, the store sweeps every attached
    registration in one event batch (store._expire emits per-lease
    batches) and each pod's ``on_lost`` callback fires here.

    - ``attach(key, on_lost)`` -> lease id to put the key under.
    - ``detach(key, delete=True)`` -> per-pod revoke: deletes only that
      key; siblings on the shared lease are untouched. The host lease
      itself is revoked when the last key detaches.
    """

    def __init__(self, store: Store, host_id: str, ttl: float = 10.0,
                 interval: float | None = None):
        self.store = store
        self.host_id = host_id
        self.ttl = ttl
        self.interval = interval if interval is not None \
            else max(0.05, ttl / 6.0)
        self._lock = threading.RLock()
        self._lease = 0                 # guarded-by: _lock
        self._attached: dict[str, object] = {}  # key -> on_lost|None
        self._stop = threading.Event()  # replaced per lease generation
        self.keepalives_sent = 0        # guarded-by: _lock
        self.leases_lost = 0            # guarded-by: _lock
        self.closed = False             # guarded-by: _lock
        self._obs = metrics.register_stats("lease_coalescer", self.stats)

    def lease(self) -> int:
        """The host lease id (granted + keepalive thread started on
        first use; re-granted after a loss)."""
        with self._lock:
            if self.closed:
                raise EdlStoreError(
                    f"lease coalescer for {self.host_id} is closed")
            if self._lease == 0:
                self._lease = self.store.lease_grant(self.ttl)
                self._stop = threading.Event()
                threading.Thread(
                    target=self._run, args=(self._lease, self._stop),
                    daemon=True,
                    name=f"host-lease-{self.host_id}").start()
            return self._lease

    def attach(self, key: str, on_lost=None) -> int:
        with self._lock:
            lease = self.lease()
            self._attached[key] = on_lost
            return lease

    def detach(self, key: str, delete: bool = False) -> None:
        with self._lock:
            self._attached.pop(key, None)
            empty = not self._attached and self._lease
        if delete:
            try:
                self.store.delete(key)
            except EdlStoreError:
                log.warning("coalescer detach: delete %r failed", key)
        if empty:
            self._retire()

    def _retire(self) -> None:
        with self._lock:
            if self._attached or not self._lease:
                return
            lease, self._lease = self._lease, 0
            self._stop.set()
        try:
            self.store.lease_revoke(lease)
        except EdlStoreError:
            pass  # ttl expiry collects it

    def _run(self, lease: int, stop: threading.Event) -> None:
        while not stop.wait(self.interval):
            try:
                alive = self.store.lease_keepalive(lease)
            except EdlStoreError as exc:
                log.warning("host lease %d keepalive error: %s", lease, exc)
                continue
            with self._lock:
                self.keepalives_sent += 1
            if not alive:
                if not stop.is_set():
                    self._on_host_lost(lease)
                return

    def _on_host_lost(self, lease: int) -> None:
        with self._lock:
            if self._lease != lease:
                return  # already retired / re-granted
            self._lease = 0
            attached = dict(self._attached)
            self._attached.clear()
            self.leases_lost += 1
        flight.record("lease_host_expire", host=self.host_id,
                      lease=lease, keys=len(attached))
        log.error("host lease %d (%s) lost: %d registrations swept",
                  lease, self.host_id, len(attached))
        for key, cb in attached.items():
            if cb is not None:
                try:
                    cb()
                except Exception:  # noqa: BLE001 — observer callbacks
                    log.exception("on_lost callback for %r failed", key)

    def stats(self) -> dict:
        with self._lock:
            return {"host": self.host_id,
                    "lease_batch_size": len(self._attached),
                    "keepalives_sent": self.keepalives_sent,
                    "leases_lost": self.leases_lost,
                    "active": 1 if self._lease else 0}

    def close(self, revoke: bool = True) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
            self._attached.clear()
            lease, self._lease = self._lease, 0
            self._stop.set()
        if revoke and lease:
            try:
                self.store.lease_revoke(lease)
            except EdlStoreError:
                pass
        metrics.unregister(self._obs)


_coalescers: dict[tuple[int, str], HostLeaseCoalescer] = {}
_coalescer_lock = threading.Lock()


def host_coalescer(store: Store, host_id: str,
                   ttl: float = 10.0) -> HostLeaseCoalescer:
    """Process-wide coalescer per (store, host): every PodRegister on
    the host shares one lease + one keepalive thread."""
    with _coalescer_lock:
        key = (id(store), host_id)
        co = _coalescers.get(key)
        if co is None or co.closed:
            co = HostLeaseCoalescer(store, host_id, ttl)
            _coalescers[key] = co
        return co
