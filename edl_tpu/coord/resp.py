"""RESP2 wire protocol: client + a minimal in-process server.

The transport half of the redis registry flavor (reference C10-C14,
`python/paddle_edl/distill/redis/` — a from-scratch epoll TCP server
speaking a framed protocol plus a redis-hash registry). Re-designed for
this stack: the protocol is real RESP2, so `RespClient` talks to a REAL
redis in deployment, and `MiniRedis` — the hand-rolled-server analogue
of the reference's `balance_server.py` — implements the command subset
the registry needs for tests and single-box runs (no redis binary or
client library exists in this image; both halves are pure sockets).

Commands MiniRedis serves: PING, SET [NX] [PX ms], GET, MGET, DEL,
KEYS, SCAN, INCR, SADD, SMEMBERS, PEXPIRE, PTTL, EXISTS, FLUSHALL,
plus PUBLISH / SUBSCRIBE (the pub/sub half of the RedisStore watch
flavor: pushed ["message", channel, payload] arrays, exactly redis's
RESP2 shape, sent to subscriber connections from the publisher's
thread under a per-connection send lock).
Expiry is millisecond-granular (PEXPIRE / SET PX) because registry TTLs
in tests are sub-second; keys expire lazily on access plus in scans.
Glob patterns honor redis semantics including backslash escapes (fnmatch
would treat an escaped `\\[` as a character class and diverge from real
redis).

Error contract: everything the client raises is `RespError`, a subclass
of `EdlStoreError` — the registry/lease machinery's retry paths catch
`EdlStoreError` (coord/registry.py), and a transient socket error must
land in those paths, not kill a keepalive thread. After any transport
error the connection is closed and lazily re-established, so a late
reply from a timed-out command can never be read as the next command's
answer.
"""

from __future__ import annotations

import re
import socket
import socketserver
import threading
import time

from edl_tpu.utils.exceptions import EdlStoreError


class RespError(EdlStoreError):
    """Transport/protocol-level failure (stream possibly desynced)."""


class RespServerError(RespError):
    """A `-ERR ...` reply from the server: the stream stays in sync."""


# -- wire --------------------------------------------------------------------

def encode_command(args: tuple) -> bytes:
    """Client command -> RESP array of bulk strings."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        data = a if isinstance(a, bytes) else str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(data), data))
    return b"".join(out)


def _read_line(rf) -> bytes:
    line = rf.readline()
    if not line.endswith(b"\r\n"):
        raise RespError("connection closed mid-reply")
    return line[:-2]


def read_reply(rf):
    """One RESP reply -> python value (str | int | None | list | error).

    Every failure mode raises RespError (bare int() ValueErrors from a
    malformed peer would otherwise escape the EdlStoreError-based retry
    paths and kill keepalive threads)."""
    line = _read_line(rf)
    if not line:
        raise RespError("empty reply")
    kind, rest = line[:1], line[1:]
    try:
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespServerError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = rf.read(n + 2)
            if len(data) != n + 2:
                raise RespError("connection closed mid-bulk")
            return data[:-2].decode()
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [read_reply(rf) for _ in range(n)]
    except (ValueError, UnicodeDecodeError) as exc:
        raise RespError(f"malformed reply {line!r}: {exc}") from exc
    raise RespError(f"unknown reply type {kind!r}")


def encode_reply(value) -> bytes:
    """Server value -> RESP bytes (str=bulk, int=:, None=nil, list=array,
    ('+', s)=simple string, ('-', s)=error)."""
    if isinstance(value, tuple) and len(value) == 2 and value[0] in "+-":
        return f"{value[0]}{value[1]}\r\n".encode()
    if value is None:
        return b"$-1\r\n"
    if isinstance(value, int):
        return b":%d\r\n" % value
    if isinstance(value, list):
        return b"*%d\r\n" % len(value) + b"".join(
            encode_reply(v) for v in value)
    data = value if isinstance(value, bytes) else str(value).encode()
    return b"$%d\r\n%s\r\n" % (len(data), data)


def redis_glob_match(pattern: str, s: str) -> bool:
    """Redis KEYS/SCAN glob semantics: * ? [set] and backslash escapes
    (fnmatch treats '\\[' as a literal backslash + class start — wrong)."""
    rx, i = [], 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern):
            rx.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "*":
            rx.append(".*")
        elif ch == "?":
            rx.append(".")
        elif ch == "[":
            j = pattern.find("]", i + 1)
            if j == -1:
                rx.append(re.escape(ch))
            else:
                rx.append(pattern[i:j + 1])
                i = j
        else:
            rx.append(re.escape(ch))
        i += 1
    return re.fullmatch("".join(rx), s) is not None


class RespClient:
    """Blocking RESP2 client (thread-safe; reconnects after any error).

    One in-flight command at a time under the lock; any transport error
    closes the socket so a stale late reply can never desynchronize the
    stream — the next command dials a fresh connection.
    """

    def __init__(self, endpoint: str, timeout: float = 10.0,
                 connect_retries: int = 30,
                 connect_interval: float = 0.3):
        from edl_tpu.utils.net import split_endpoint
        self._addr = split_endpoint(endpoint)
        self._timeout = timeout
        self._connect_retries = connect_retries
        self._connect_interval = connect_interval
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._rf = None
        self._connect()  # surface an unreachable endpoint at build time

    def _connect(self) -> None:
        # Bounded retry (like StoreClient._connect): in a pod/compose
        # bring-up the client often starts a beat before its server
        # accepts connections.
        last: Exception | None = None
        for _ in range(max(1, self._connect_retries)):
            try:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                break
            except OSError as exc:
                last = exc
                time.sleep(self._connect_interval)
        else:
            raise RespError(f"cannot connect to {self._addr}: {last}")
        self._sock.settimeout(self._timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rf = self._sock.makefile("rb")

    def _teardown(self) -> None:
        try:
            if self._rf is not None:
                self._rf.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock, self._rf = None, None

    def command(self, *args):
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall(encode_command(args))
                return read_reply(self._rf)
            except RespServerError:
                raise  # a -ERR reply: the stream stays in sync
            except RespError:
                # any transport/parse failure may leave unread bytes —
                # tear down so a stale late reply can never be read as
                # the next command's answer
                self._teardown()
                raise
            except OSError as exc:
                self._teardown()
                raise RespError(f"transport error: {exc}") from exc

    def close(self) -> None:
        with self._lock:
            self._teardown()


# -- minimal server ----------------------------------------------------------

class _Subscriber:
    """One subscribed connection: socket + send lock (pushed messages
    come from publisher threads, replies from the handler thread — the
    lock keeps frames from interleaving mid-write)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.send_lock = threading.Lock()

    def send(self, value) -> None:
        with self.send_lock:
            self.sock.sendall(encode_reply(value))


class _State:
    def __init__(self):
        self.lock = threading.RLock()
        self.strings: dict[str, str] = {}
        self.sets: dict[str, set] = {}
        self.deadlines: dict[str, float] = {}  # key -> monotonic deadline
        self.subscribers: dict[str, set[_Subscriber]] = {}

    # -- pub/sub (socket-touching: called from _Handler, not execute) -------

    def subscribe(self, channel: str, sub: _Subscriber) -> int:
        with self.lock:
            self.subscribers.setdefault(channel, set()).add(sub)
            return sum(1 for subs in self.subscribers.values()
                       if sub in subs)

    def unsubscribe(self, sub: _Subscriber) -> None:
        with self.lock:
            for subs in self.subscribers.values():
                subs.discard(sub)

    def publish(self, channel: str, message: str) -> int:
        with self.lock:
            subs = list(self.subscribers.get(channel, ()))
        delivered = 0
        for sub in subs:
            try:
                sub.send(["message", channel, message])
                delivered += 1
            except OSError:
                self.unsubscribe(sub)  # dead subscriber: drop it
        return delivered

    def _alive(self, key: str) -> bool:
        dl = self.deadlines.get(key)
        if dl is not None and dl <= time.monotonic():
            self.strings.pop(key, None)
            self.sets.pop(key, None)
            self.deadlines.pop(key, None)
            return False
        return key in self.strings or key in self.sets

    def _live_keys(self, pattern: str) -> list[str]:
        keys = set(self.strings) | set(self.sets)  # a key can be both
        return sorted(k for k in keys
                      if self._alive(k) and redis_glob_match(pattern, k))

    def execute(self, args: list[str]):
        cmd, rest = args[0].upper(), args[1:]
        with self.lock:
            if cmd == "PING":
                return ("+", "PONG")
            if cmd == "SET":
                key, val, *opts = rest
                nx = px_ms = None
                i = 0
                while i < len(opts):
                    o = opts[i].upper()
                    if o == "NX":
                        nx = True
                    elif o == "PX" and i + 1 < len(opts):
                        px_ms = int(opts[i + 1])
                        i += 1
                    i += 1
                # real redis's NX is type-agnostic: any live key blocks
                if nx and self._alive(key):
                    return None
                self.sets.pop(key, None)  # SET replaces any type
                self.strings[key] = val
                if px_ms is not None:
                    self.deadlines[key] = time.monotonic() + px_ms / 1000.0
                else:
                    self.deadlines.pop(key, None)
                return ("+", "OK")
            if cmd == "GET":
                key = rest[0]
                return self.strings.get(key) if self._alive(key) else None
            if cmd == "MGET":
                return [self.strings.get(k) if self._alive(k) else None
                        for k in rest]
            if cmd == "DEL":
                n = 0
                for k in rest:
                    alive = self._alive(k)
                    if (k in self.strings or k in self.sets) and alive:
                        n += 1
                    self.strings.pop(k, None)
                    self.sets.pop(k, None)
                    self.deadlines.pop(k, None)
                return n
            if cmd == "EXISTS":
                return sum(1 for k in rest
                           if self._alive(k) and k in self.strings)
            if cmd == "KEYS":
                return self._live_keys(rest[0])
            if cmd == "SCAN":
                # single-batch cursor: reply ["0", [keys]] is legal SCAN
                pattern = "*"
                for i, o in enumerate(rest[1:], 1):
                    if o.upper() == "MATCH" and i + 1 <= len(rest) - 1:
                        pattern = rest[i + 1]
                return ["0", self._live_keys(pattern)]
            if cmd == "INCR":
                key = rest[0]
                cur = int(self.strings.get(key, "0")) \
                    if self._alive(key) else 0
                self.strings[key] = str(cur + 1)
                return cur + 1
            if cmd == "SADD":
                key, *members = rest
                self._alive(key)
                s = self.sets.setdefault(key, set())
                before = len(s)
                s.update(members)
                return len(s) - before
            if cmd == "SREM":
                key, *members = rest
                if not self._alive(key):
                    return 0
                s = self.sets.get(key, set())
                n = len(s & set(members))
                s.difference_update(members)
                return n
            if cmd == "SMEMBERS":
                key = rest[0]
                return sorted(self.sets.get(key, set())) \
                    if self._alive(key) else []
            if cmd == "PEXPIRE":
                key, ms = rest[0], int(rest[1])
                if not self._alive(key):
                    return 0
                self.deadlines[key] = time.monotonic() + ms / 1000.0
                return 1
            if cmd == "PTTL":
                key = rest[0]
                if not self._alive(key):
                    return -2
                dl = self.deadlines.get(key)
                if dl is None:
                    return -1
                return max(0, int((dl - time.monotonic()) * 1000))
            if cmd == "FLUSHALL":
                self.strings.clear()
                self.sets.clear()
                self.deadlines.clear()
                return ("+", "OK")
            return ("-", f"ERR unknown command '{cmd}'")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        state: _State = self.server.state  # type: ignore[attr-defined]
        rf = self.request.makefile("rb")
        sub: _Subscriber | None = None
        try:
            while True:
                try:
                    cmd = read_reply(rf)
                except RespError:
                    return  # disconnect / garbage: drop the connection
                if not isinstance(cmd, list) or not cmd:
                    return
                args = [str(c) for c in cmd]
                name = args[0].upper()
                try:
                    if name == "SUBSCRIBE":
                        if sub is None:
                            sub = _Subscriber(self.request)
                        for channel in args[1:]:
                            n = state.subscribe(channel, sub)
                            sub.send(["subscribe", channel, n])
                        continue
                    if name == "PUBLISH":
                        reply = state.publish(args[1], args[2])
                    else:
                        reply = state.execute(args)
                except OSError:
                    return
                except Exception as exc:  # noqa: BLE001 — to the client
                    reply = ("-", f"ERR {type(exc).__name__}: {exc}")
                try:
                    if sub is not None:
                        sub.send(reply)
                    else:
                        self.request.sendall(encode_reply(reply))
                except OSError:
                    return
        finally:
            if sub is not None:
                state.unsubscribe(sub)
            rf.close()


class MiniRedis:
    """In-process RESP2 server over the command subset above."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self._server.state = _State()  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self.endpoint = f"{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-redis")

    def start(self) -> "MiniRedis":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
