"""Consistent hash ring with virtual nodes and copy-on-write snapshots.

Capability parity: reference discovery/consistent_hash.py:21-141 (md5 ring,
300 virtual nodes, copy-on-write reads so a single writer needs no reader
locks, versioned snapshots). Used by the distill balancer to shard service
names across discovery replicas with REDIRECT responses
(distill/balance_table.py:363-433).

Design: an immutable ``_Ring`` snapshot (sorted hash points + bisect lookup)
swapped atomically under a writer lock; readers grab ``self._ring`` once —
Python reference assignment is atomic — and never block.
"""

from __future__ import annotations

import bisect
import hashlib
import threading


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode("utf-8")).digest()[:8], "big")


class _Ring:
    __slots__ = ("points", "owners", "nodes", "version")

    def __init__(self, nodes: frozenset[str], vnodes: int, version: int):
        pairs = sorted(
            (_hash(f"{node}#{i}"), node)
            for node in nodes
            for i in range(vnodes)
        )
        self.points = [p for p, _ in pairs]
        self.owners = [n for _, n in pairs]
        self.nodes = nodes
        self.version = version

    def lookup(self, key: str) -> str | None:
        if not self.points:
            return None
        idx = bisect.bisect_right(self.points, _hash(key)) % len(self.points)
        return self.owners[idx]


class ConsistentHash:
    def __init__(self, nodes: list[str] | None = None, vnodes: int = 300):
        self._vnodes = vnodes
        self._write_lock = threading.Lock()
        self._ring = _Ring(frozenset(nodes or ()), vnodes, version=0)

    @property
    def version(self) -> int:
        return self._ring.version

    @property
    def nodes(self) -> frozenset[str]:
        return self._ring.nodes

    def add_node(self, node: str) -> None:
        with self._write_lock:
            ring = self._ring
            if node in ring.nodes:
                return
            self._ring = _Ring(ring.nodes | {node}, self._vnodes,
                               ring.version + 1)

    def remove_node(self, node: str) -> None:
        with self._write_lock:
            ring = self._ring
            if node not in ring.nodes:
                return
            self._ring = _Ring(ring.nodes - {node}, self._vnodes,
                               ring.version + 1)

    def set_nodes(self, nodes: list[str]) -> None:
        with self._write_lock:
            new = frozenset(nodes)
            if new != self._ring.nodes:
                self._ring = _Ring(new, self._vnodes, self._ring.version + 1)

    def lookup(self, key: str) -> str | None:
        return self._ring.lookup(key)

    def lookup_with_version(self, key: str) -> tuple[str | None, int]:
        ring = self._ring
        return ring.lookup(key), ring.version
