from edl_tpu.coord.store import (Event, InMemStore, Record, Store, Watch,
                                 WatchBatch, try_watch, watch_enabled)
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.lock import DistributedLock, LeaderElection
from edl_tpu.coord.redis_store import RedisStore, connect_store
from edl_tpu.coord.registry import ServiceRegistry, ServerMeta
from edl_tpu.coord.resp import MiniRedis
from edl_tpu.coord.collector import Collector, UtilizationPublisher
from edl_tpu.coord.consistent_hash import ConsistentHash


def __getattr__(name):
    # Lazy so `python -m edl_tpu.coord.server` / `.replication` don't
    # import their module twice (runpy RuntimeWarning).
    if name == "StoreServer":
        from edl_tpu.coord.server import StoreServer
        return StoreServer
    if name in ("ReplicaNode", "ReplicaServer", "ReplicaGroup",
                "ShardedStoreClient", "ShardRouter", "shard_key",
                "parse_topology"):
        from edl_tpu.coord import replication
        return getattr(replication, name)
    raise AttributeError(name)

__all__ = [
    "Store",
    "InMemStore",
    "Record",
    "Event",
    "Watch",
    "WatchBatch",
    "try_watch",
    "watch_enabled",
    "StoreClient",
    "StoreServer",
    "RedisStore",
    "MiniRedis",
    "connect_store",
    "DistributedLock",
    "LeaderElection",
    "ServiceRegistry",
    "ServerMeta",
    "ConsistentHash",
    "Collector",
    "UtilizationPublisher",
    "ReplicaNode",
    "ReplicaServer",
    "ReplicaGroup",
    "ShardedStoreClient",
    "ShardRouter",
    "shard_key",
    "parse_topology",
]
