"""Cluster metrics collector + trainer utilization publishing.

Capability of the reference's scheduler data path
(example/fit_a_line/collector.py:51-130 polls the cluster for
phase/utilization metrics; discovery/register.py:36-40 reserves the
registry ``info`` field for "report job performance to the scheduler"),
redesigned over OUR source of truth: the coordination store, not the
Kubernetes API — the store already holds live pod claims, the published
cluster generation, and every service registrar's serving counters, so a
scheduler gets one scrape point that works identically on k8s, bare
metal, and in tests.

Two halves:

- `UtilizationPublisher` — trainer-side. A TrainLoop hook (same
  ``(loop, epoch, step, metrics)`` signature) that writes this pod's
  ``{epoch, step, samples_seen, examples_per_sec, world_size,
  generation, published_unix}`` to the leased key
  ``/{job}/util/{pod_id}``; the lease makes staleness self-cleaning (a
  dead trainer's utilization disappears after TTL). ``world_size`` is
  the ELASTIC world (launcher pod count, EDL_TPU_WORLD_SIZE) — the
  unit the scaler allocates in — not the device world. TrainLoop
  installs one automatically when running under the elastic launcher
  (EDL_TPU_RANK set) unless EDL_TPU_PUBLISH_UTIL=0.
- `Collector` — scheduler-side. Snapshots a job (live rank claims,
  published cluster generation, per-pod utilization) + any service
  registries (teacher ``busy_s``/``served_rows``/... from
  TeacherRegistrar stats, plus per-service pool rollups —
  ``service_rollup`` sums rates/queues, means utilization, and takes
  the worst teacher's latency tail: the serving scaler's view) + store
  health (revision, key/leased-key counts), emitted as one JSON
  object; the CLI prints one line per tick for a scheduler to consume:

      python -m edl_tpu.coord.collector --store h:p --job jid \
          --services svc --interval 5
"""

from __future__ import annotations

import argparse
import json
import queue
import sys
import threading
import time
from typing import Any

from edl_tpu.coord.store import Store
from edl_tpu.obs import trace
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.coord.collector")


def util_prefix(job_id: str) -> str:
    return f"/{job_id}/util/"


def util_key(job_id: str, pod_id: str) -> str:
    return f"/{job_id}/util/{pod_id}"


class UtilizationPublisher:
    """Publish trainer progress to the pod's leased utilization record.

    Callable with the TrainLoop hook signature, so wiring it is:
    ``TrainLoop(..., hooks=[UtilizationPublisher(store, job, pod)])``.

    The hook itself never touches the store: ``__call__`` builds the
    document and drops it into a ONE-SLOT latest-wins mailbox; a
    background thread owns the lease and the store writes. A hung or
    slow store therefore can't stall a train step for its timeout —
    the worst case before r6, where every log-point put rode the
    training thread for up to the store's ~10 s timeout. Publishing
    stays best-effort with a cooldown after failures; ``flush()``
    waits for the mailbox to drain (tests, orderly shutdown).
    """

    def __init__(self, store: Store, job_id: str, pod_id: str, *,
                 rank: int = -1, ttl: float = 15.0,
                 min_interval: float = 1.0, generation: int | None = None,
                 world_size: int | None = None):
        self.store = store
        self.job_id = job_id
        self.pod_id = pod_id
        self.rank = rank
        self.ttl = ttl
        self.min_interval = min_interval
        # cluster generation this trainer was launched into (the scaler
        # correlates a rate with the allocation that produced it)
        self.generation = generation
        # the ELASTIC world — launcher pod count (EDL_TPU_WORLD_SIZE),
        # the same unit as Cluster.world_size and the scaler's node
        # allocations. NOT loop.status.world_size, which is the device
        # world (jax.device_count() / mesh dp size): with >1 device per
        # pod the two differ and the scaler's pre-resize filter would
        # drop every record. None = unknown (standalone hook): the doc
        # carries null and the scaler skips the cross-world filter.
        self.world_size = world_size
        # `published_unix` must be monotonic per pod even across clock
        # hiccups: the scaler's staleness check subtracts it from now()
        self._pub_unix = 0.0             # guarded-by: _lock
        self._lease: int | None = None
        self._keeper = None
        self._lock = threading.Lock()
        # flush() blocks on this instead of spinning: notified whenever
        # _pending reaches zero (the bench host has ONE core — a 10 ms
        # sleep-poll loop here measurably stole it from training)
        self._drained = threading.Condition(self._lock)
        self._last_pub = 0.0             # guarded-by: _lock
        # rate window seeds on the FIRST call: samples_seen may restore
        # non-zero from a checkpoint, and measuring from 0 would report
        # a wildly inflated examples_per_sec right after every resize
        self._last_samples: int | None = None  # guarded-by: _lock
        self._last_t = time.monotonic()  # guarded-by: _lock
        # publisher-thread-only until stop() joins it (happens-before)
        self._cooldown_until = 0.0
        self._owns_store = False  # from_env's connection: close on stop
        # latest-wins mailbox + lazily-started publisher thread
        self._mailbox: "queue.Queue[dict]" = queue.Queue(maxsize=1)
        # snapshots enqueued, unpublished
        self._pending = 0                # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # set by the TrainLoop at an adoption/peer restore: the resize
        # trace's span context. The FIRST successful publish after it
        # is the 'first fresh util at the new world' — the publisher
        # emits a zero-duration marker span into that trace and clears
        # it (one marker per resize; latest-wins slot, a benign
        # single-attribute handoff between the training thread and the
        # publisher thread).
        self.resize_trace: tuple[str, str] | None = None

    @classmethod
    def from_env(cls) -> "UtilizationPublisher | None":
        """Build from the launcher's trainer env (TRAINER_ENV_VARS);
        None when not under the elastic launcher or opted out."""
        from edl_tpu.utils import config
        if not config.env_flag("EDL_TPU_PUBLISH_UTIL", True):
            return None
        if not config.env_present("EDL_TPU_RANK"):
            return None  # standalone run: nothing to publish into
        endpoints = config.env_str("EDL_TPU_STORE_ENDPOINTS", "") or ""
        job_id = config.env_str("EDL_TPU_JOB_ID", "") or ""
        pod_id = config.env_str("EDL_TPU_POD_ID", "") or ""
        if not (endpoints and job_id and pod_id):
            return None
        from edl_tpu.coord.redis_store import connect_store
        try:
            store = connect_store(endpoints.split(",")[0])
        except Exception as exc:  # noqa: BLE001 — never block training
            log.warning("utilization publisher disabled (store "
                        "unreachable: %s)", exc)
            return None
        world = config.env_int("EDL_TPU_WORLD_SIZE", 0)
        pub = cls(store, job_id, pod_id,
                  rank=config.env_int("EDL_TPU_RANK", -1),
                  generation=config.env_int("EDL_TPU_CLUSTER_VERSION",
                                            0) or None,
                  world_size=world or None)
        pub._owns_store = True
        return pub

    def _ensure_lease(self) -> int:
        if self._lease is not None and self._keeper is not None \
                and not self._keeper.lost.is_set():
            return self._lease
        from edl_tpu.coord.client import LeaseKeeper
        if self._keeper is not None:
            self._keeper.stop(revoke=False)
        self._lease = self.store.lease_grant(self.ttl)
        self._keeper = LeaseKeeper(self.store, self._lease,
                                   interval=self.ttl / 6.0).start()
        return self._lease

    def __call__(self, loop, epoch: int, step: int,
                 metrics: dict | None = None) -> None:
        """Training-thread side: bookkeeping + mailbox drop only — no
        store I/O ever happens here."""
        now = time.monotonic()
        with self._lock:
            if self._stop.is_set() \
                    or now - self._last_pub < self.min_interval:
                return
            samples = int(getattr(loop.status, "samples_seen", 0)) \
                if loop is not None else 0
            if self._last_samples is None:  # first call: no window yet
                self._last_samples = samples
                self._last_t = now
            rate = (samples - self._last_samples) / max(
                now - self._last_t, 1e-9) if samples > self._last_samples \
                else 0.0
            # scaler contract: `published_unix` (monotonic non-decreasing
            # staleness anchor — lease TTL alone only bounds death, not
            # stale rates) + `world_size` (the POD-COUNT allocation this
            # rate was measured under — Cluster.world_size's unit — so
            # pre-resize records are filterable against the live world).
            self._pub_unix = max(time.time(), self._pub_unix + 1e-4)
            doc = {"pod_id": self.pod_id, "rank": self.rank,
                   "epoch": int(epoch), "step": int(step),
                   "samples_seen": samples,
                   "examples_per_sec": round(max(rate, 0.0), 2),
                   "world_size": self.world_size,
                   "generation": self.generation,
                   "published_unix": round(self._pub_unix, 4),
                   "ts": time.time()}
            self._last_pub = now
            self._last_samples = samples
            self._last_t = now
            # latest-wins: a stalled publisher drops the OLD snapshot
            while True:
                try:
                    self._mailbox.put_nowait(doc)
                    self._pending += 1
                    break
                except queue.Full:
                    try:
                        self._mailbox.get_nowait()
                        self._pending -= 1
                    except queue.Empty:
                        pass
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._publish_loop, daemon=True,
                    name="util-publisher")
                self._thread.start()

    def _publish_loop(self) -> None:
        while not self._stop.is_set():
            try:
                doc = self._mailbox.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._publish(doc)
            finally:
                with self._lock:
                    self._pending -= 1
                    if self._pending <= 0:
                        self._drained.notify_all()

    def _publish(self, doc: dict) -> None:
        now = time.monotonic()
        if now < self._cooldown_until:
            return
        try:
            self.store.put(util_key(self.job_id, self.pod_id),
                           json.dumps(doc, sort_keys=True),
                           lease=self._ensure_lease())
            ctx, self.resize_trace = self.resize_trace, None
            if ctx is not None:
                # first utilization record published at the new world:
                # the tail of the resize trace (decision -> actuation ->
                # restore/adopt -> THIS)
                trace.instant("resize.first_fresh_util", parent=ctx,
                              attrs={"pod": self.pod_id,
                                     "world": doc.get("world_size"),
                                     "generation": doc.get("generation")})
        except Exception as exc:  # noqa: BLE001 — best-effort: a
            # publishing failure of ANY kind must never kill training
            log.warning("utilization publish failed (%s); pausing 30s", exc)
            self._cooldown_until = now + 30.0
            self._lease = None

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait for every enqueued snapshot to be published (or dropped
        by the cooldown); True when the mailbox drained in time. Blocks
        on a condition (no spin: the publisher thread notifies when the
        last snapshot lands)."""
        with self._drained:
            return self._drained.wait_for(lambda: self._pending <= 0,
                                          timeout=timeout)

    def stop(self) -> None:
        self.flush(timeout=2.0)   # best-effort final snapshot
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            if self._keeper is not None:
                self._keeper.stop(revoke=True)
                self._keeper = None
                self._lease = None
            if self._owns_store:
                self._owns_store = False
                try:
                    self.store.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass


def _parse_info(info: str) -> Any:
    if not info:
        return {}
    try:
        return json.loads(info)
    except json.JSONDecodeError:
        return info  # registrars may publish plain strings


class Collector:
    """One scrape point for a scheduler: job membership + utilization,
    service registries, store health (module docstring has the map)."""

    def __init__(self, store: Store, job_id: str | None = None,
                 services: tuple[str, ...] = (),
                 registry_root: str = "edl"):
        self.store = store
        self.job_id = job_id
        self.services = tuple(services)
        self.registry_root = registry_root

    def _job_snapshot(self, job_id: str) -> dict:
        from edl_tpu.collective import register as reg
        from edl_tpu.collective.cluster import Cluster
        pods, _ = reg.live_pods(self.store, job_id)
        cluster_rec = self.store.get(reg.cluster_key(job_id))
        generation, world = None, None
        if cluster_rec is not None:
            cluster = Cluster.from_json(cluster_rec.value)
            generation, world = cluster.version, cluster.world_size
        util_recs, _ = self.store.get_prefix(util_prefix(job_id))
        util = {}
        for rec in util_recs:
            util[rec.key.rsplit("/", 1)[-1]] = _parse_info(rec.value)
        complete = self.store.get(reg.complete_key(job_id)) is not None
        return {"job_id": job_id,
                "generation": generation,
                "world_size": world,
                "complete": complete,
                "pods": [{"pod_id": p.pod_id,
                          "claimed_rank": p.claimed_rank,
                          "addr": p.addr, "n_devices": p.n_devices,
                          "utilization": util.get(p.pod_id)}
                         for p in pods]}

    def _service_snapshot(self, service: str) -> list[dict]:
        from edl_tpu.coord.registry import ServiceRegistry
        registry = ServiceRegistry(self.store, root=self.registry_root)
        return [{"server": m.server, "info": _parse_info(m.info)}
                for m in registry.get_service(service)]

    def service_rollup(self, service: str) -> dict:
        """Pool-level digest of one service registry — what the serving
        scaler consumes. Rates and queue depths SUM across teachers
        (pool capacity / pool backlog); ``util`` is the mean busy
        fraction (the low-water shrink signal); latency quantiles take
        the WORST reporting teacher — the pool's p95 is its slowest
        member's tail, and a conservative read can only over-provision,
        never silently violate the SLO. ``reporting`` counts teachers
        whose registrar published a parseable info doc: ``n_teachers``
        without ``reporting`` means a pool that is up but blind.

        Admission-control signals (r23 registrars) roll up alongside:
        ``shed_per_sec`` SUMS (pool-wide rejection pressure — the
        policy's shed-blinded-breach input: an admission-controlled
        pool keeps its p95 in-SLO *by rejecting*, so latency alone
        under-reports overload), ``queue_depth_by_class`` sums per
        class, and ``latency_ms_p95_by_class`` takes the worst teacher
        per class (graceful degradation is judged per class, not
        globally). ``draining`` counts teachers mid-drain."""
        rows, depth, inflight = 0.0, 0, 0
        shed, draining = 0.0, 0
        utils: list[float] = []
        p50s: list[float] = []
        p95s: list[float] = []
        depth_by_class: dict[str, int] = {}
        p95_by_class: dict[str, float] = {}
        members = self._service_snapshot(service)
        reporting = 0
        for m in members:
            info = m["info"]
            if not isinstance(info, dict) or not info:
                continue  # no/unparseable/empty info: a blind member
            reporting += 1
            rows += float(info.get("rows_per_sec") or 0.0)
            depth += int(info.get("queue_depth") or 0)
            inflight += int(info.get("inflight_groups") or 0)
            shed += float(info.get("shed_per_sec") or 0.0)
            draining += 1 if info.get("draining") else 0
            if info.get("util") is not None:
                utils.append(float(info["util"]))
            if info.get("latency_ms_p50") is not None:
                p50s.append(float(info["latency_ms_p50"]))
            if info.get("latency_ms_p95") is not None:
                p95s.append(float(info["latency_ms_p95"]))
            split = info.get("queue_depth_by_class")
            if isinstance(split, dict):
                for cls, n in split.items():
                    try:
                        depth_by_class[str(cls)] = (
                            depth_by_class.get(str(cls), 0) + int(n))
                    except (TypeError, ValueError):
                        pass
            lat_split = info.get("latency_ms_p95_by_class")
            if isinstance(lat_split, dict):
                for cls, p95 in lat_split.items():
                    try:
                        p95_by_class[str(cls)] = max(
                            p95_by_class.get(str(cls), 0.0), float(p95))
                    except (TypeError, ValueError):
                        pass
        return {"service": service,
                "n_teachers": len(members),
                "reporting": reporting,
                "rows_per_sec": round(rows, 2),
                "util": (round(sum(utils) / len(utils), 4)
                         if utils else None),
                "queue_depth": depth,
                "inflight_groups": inflight,
                "latency_ms_p50": max(p50s) if p50s else None,
                "latency_ms_p95": max(p95s) if p95s else None,
                "shed_per_sec": round(shed, 2),
                "queue_depth_by_class": depth_by_class,
                "latency_ms_p95_by_class": p95_by_class,
                "draining": draining}

    def snapshot(self) -> dict:
        records, revision = self.store.get_prefix("")
        doc: dict = {"ts": time.time(),
                     "store": {"revision": revision,
                               "keys": len(records),
                               "leased_keys": sum(
                                   1 for r in records if r.lease)}}
        if self.job_id:
            doc["job"] = self._job_snapshot(self.job_id)
        if self.services:
            doc["services"] = {s: self._service_snapshot(s)
                               for s in self.services}
            doc["service_rollups"] = {s: self.service_rollup(s)
                                      for s in self.services}
        return doc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.coord.collector",
        description="Scrape job/service/store metrics as JSON lines")
    parser.add_argument("--store", required=True,
                        help="store endpoint (host:port or redis://...)")
    parser.add_argument("--job", default="",
                        help="job id to snapshot (/{job}/ keys)")
    parser.add_argument("--services", default="",
                        help="comma-joined service registry names")
    parser.add_argument("--registry-root", default="edl")
    parser.add_argument("--interval", type=float, default=5.0)
    parser.add_argument("--once", action="store_true",
                        help="emit one snapshot and exit")
    args = parser.parse_args(argv)

    from edl_tpu.coord.redis_store import connect_store
    store = connect_store(args.store)
    services = tuple(s for s in args.services.split(",") if s)
    collector = Collector(store, job_id=args.job or None,
                          services=services,
                          registry_root=args.registry_root)
    try:
        while True:
            print(json.dumps(collector.snapshot(), sort_keys=True),
                  flush=True)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        store.close()


if __name__ == "__main__":
    sys.exit(main())
