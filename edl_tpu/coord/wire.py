"""Framed-JSON wire protocol shared by the store server/client.

Frame = 4-byte magic ``EDL1`` + uint32 big-endian body length + UTF-8 JSON
body. Requests are ``{"op": str, ...args}``; responses are
``{"ok": true, ...}`` or ``{"ok": false, "error": str}``. The C++
``edl-store`` daemon (native/store/) speaks the same frames, so the Python
client works against either server.

Every op is one request -> one response, except ``watch``, which turns the
connection into a long-lived server-push stream:

    client -> {"op": "watch", "prefix": str,
               "start_revision": int | null, "heartbeat": float}
    server -> {"ok": true, "watching": true, "revision": int}   # ack; the
              # revision is the watch's creation anchor (resume point when
              # start_revision was null)
    server -> {"ok": true, "events": [[type, key, value, revision], ...],
               "revision": int, "compacted": bool}              # repeated

Event frames are **range-batched**: one frame carries up to
``MAX_EVENTS_PER_FRAME`` revision-ordered events under a single
``revision`` header (the resume anchor of the LAST event in the frame) —
a multi-key mutation (lease-expiry sweep, delete_prefix, a commit-gate
release) or a burst against a lagging consumer costs one header + one
syscall, not one per event. An empty ``events`` frame is a heartbeat
(sent every ``heartbeat`` seconds when idle) whose ``revision`` advances
the client's resume anchor and doubles as liveness. A frame with
``compacted: true`` means events were lost (history compaction or a lagging
watcher queue): the client must resync with ``get_prefix`` and may resume
from that frame's revision. There is no cancel op — the client closes the
connection. The full resume/compaction contract is doc/design_coord.md.

Replicated topologies (coord/replication.py) add two structured refusal
shapes on top of the ``{"ok": false}`` envelope — refusals, not
transport errors, so the op was definitively NOT applied and a client
may re-route even non-idempotent ops (put_if_absent/cas) safely:

    {"ok": false, "not_leader": true, "leader": "host:port" | null,
     "error": "..."}                       # write sent to a follower;
                                           # `leader` is a routing hint
    {"ok": false, "redirect": true, "group": str,
     "endpoints": ["host:port", ...], "error": "..."}
                                           # key owned by another shard
                                           # group (SURVEY C3's REDIRECT)

Replica peers also exchange ``repl_probe`` / ``repl_append`` /
``repl_digest`` / ``repl_snapshot`` / ``status`` ops over the same frames
(``repl_digest`` answers a per-key [key, revision, crc32] fingerprint so
the leader can ship a delta-compressed ``repl_snapshot``; schema in
coord/replication.py). ``elect_space: true`` on a request routes it to
the replica's ALWAYS-ACTIVE election sidecar store instead of the
replicated data store — the election substrate must keep expiring
leases while the data store is a passive follower.

(The reference's redis balancer path uses an analogous hand-rolled framed
protocol: distill/redis/balance_server.py:27-32. Ours differs in magic,
framing and message schema by design.)
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any

from edl_tpu.obs import trace
from edl_tpu.utils import config

MAGIC = b"EDL1"
_HEADER = struct.Struct(">4sI")
MAX_BODY = 64 * 1024 * 1024
# ceiling on events coalesced into one watch push frame: bounds frame
# size (and a consumer's catch-up stall) while keeping the per-frame
# header/syscall cost amortized across a burst
MAX_EVENTS_PER_FRAME = 512


class WireError(ConnectionError):
    pass


# Chaos seam (edl_tpu/chaos/faults.py): an installed hook sees every
# frame at THIS boundary — send side before bytes leave, recv side after
# the body arrives — and may delay (sleep), drop (raise WireError),
# hard-close the socket, or garble the received bytes. The hook lives at
# the wire module, not monkeypatched into callers, so every consumer of
# the framed protocol (store client/server, replication senders,
# election sidecars) is faultable through one switch.
_fault_hook = None


def install_fault_hook(hook):
    """Install (or clear, with None) the wire fault hook; returns the
    previous hook so a scoped injector can restore it."""
    global _fault_hook
    prev, _fault_hook = _fault_hook, hook
    return prev


def stall_timeout() -> float:
    """Mid-frame stall deadline in seconds (EDL_TPU_WIRE_STALL_S; <=0
    disables). IDLE sockets may block per their own timeout policy —
    request/response connections legitimately sit quiet — but once a
    frame has started arriving, the rest must keep flowing: a peer that
    stalls mid-frame (SIGSTOP, half-open TCP, a chaos injector) becomes
    a typed WireError instead of a wedged consumer thread."""
    return config.env_float("EDL_TPU_WIRE_STALL_S", 60.0)


def send_msg(sock: socket.socket, msg: dict[str, Any]) -> None:
    if "op" in msg:
        # Trace seam (edl_tpu/obs/trace.py): requests carry the active
        # span context under the reserved "_tc" key (copy-on-attach, a
        # no-op when tracing is off), so server-side work joins the
        # caller's trace — one resize reads as ONE causal tree across
        # the store hop. Responses/pushes are never stamped.
        msg = trace.attach(msg)
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    hook = _fault_hook
    if hook is not None:
        hook.on_send(sock, _HEADER.size + len(body))
    sock.sendall(_HEADER.pack(MAGIC, len(body)) + body)


def _recv_exact(sock: socket.socket, n: int, *, stall: float = 0.0,
                mid_frame: bool = False) -> bytes:
    """Read exactly ``n`` bytes. With ``stall`` > 0, bytes after the
    first (or ALL bytes when ``mid_frame`` — the frame started in an
    earlier read) must each arrive within ``stall`` seconds; a socket
    whose own timeout is already tighter keeps it."""
    buf = bytearray()
    prev = sock.gettimeout()
    bounded = False
    try:
        while len(buf) < n:
            want_bound = stall > 0 and (mid_frame or buf) \
                and (prev is None or prev > stall)
            if want_bound != bounded:
                sock.settimeout(stall if want_bound else prev)
                bounded = want_bound
            try:
                chunk = sock.recv(n - len(buf))
            except TimeoutError as exc:
                if bounded:
                    raise WireError(
                        f"peer stalled mid-frame ({len(buf)}/{n} bytes "
                        f"after {stall:.0f}s)") from exc
                raise
            if not chunk:
                raise WireError("peer closed connection")
            buf.extend(chunk)
    finally:
        if bounded:
            sock.settimeout(prev)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> dict[str, Any]:
    stall = stall_timeout()
    magic, length = _HEADER.unpack(
        _recv_exact(sock, _HEADER.size, stall=stall))
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if length > MAX_BODY:
        raise WireError(f"frame too large: {length}")
    body = _recv_exact(sock, length, stall=stall, mid_frame=True)
    hook = _fault_hook
    if hook is not None:
        body = hook.on_recv(sock, body, "body")
    try:
        return json.loads(body)
    except (ValueError, UnicodeDecodeError) as exc:
        raise WireError(f"malformed frame body: {exc}") from exc
