"""Watch relay tier: fan-out proxy between the store and the fleet.

The 100k-pod control plane's multiplier (doc/design_coord.md): a
replicated follower sustains ~hundreds of direct watch streams, so the
relay subscribes **once upstream per distinct prefix** and
re-multiplexes that single stream to thousands of downstream watchers —
the shape of etcd's gRPC proxy watch coalescing. Downstreams speak the
exact store wire protocol (``RelayServer`` serves the same ``watch`` op
with the same ack/event/heartbeat frames), so a consumer cannot tell a
relay from a store server, and ``EDL_TPU_RELAY_ENDPOINTS`` re-points
every ``StoreClient.watch`` at the tier with no call-site changes.

Contract preserved end to end (the part that makes a relay safe):

- **Revision resume**: a downstream attaching at ``start_revision`` is
  fenced at it (``min_revision``) — nothing at or below is ever
  re-delivered, including by an upstream reconnect replay. Late
  attachers replay from the relay's bounded per-prefix history; a
  resume point older than the history window gets an explicit
  ``compacted`` batch (resync via ``get_prefix``), exactly as the store
  itself answers.
- **Commit gating**: the relay never invents resume anchors. Every
  revision it advertises (event frames, heartbeats) was first delivered
  by the upstream store, which only releases majority-committed
  revisions (r20's fan-out gate) — so an anchor can never name a doomed
  leader's uncommitted suffix, even through two hops.
- **Relay death == server restart**: downstream ``ClientWatch``
  reconnects with jittered backoff and resumes by revision; a restarted
  relay re-subscribes upstream from that revision and the store's event
  history replays the gap. Zero lost, zero duplicated — verified by
  ``selftest`` here and at 100k-pod scale by ``tools/store_bench.py
  --fleet``.

Layering: stdlib-only (layers.toml pins coord jax/numpy-free) — the
relay tier runs on scheduler nodes with no accelerator stack.
"""

from __future__ import annotations

import argparse
import socket
import socketserver
import threading
import time

from edl_tpu.coord import wire
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.store import WatchBatch
from edl_tpu.obs import metrics, trace
from edl_tpu.obs import recorder as flight
from edl_tpu.utils import config
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.coord.relay")

# a downstream this many undrained batches behind is collapsed to a
# compacted resync instead of buffering without bound
_MAX_SUB_BATCHES = 256


def relay_buffer(default: int = 4096) -> int:
    """Per-prefix replay-history length (EDL_TPU_RELAY_BUFFER): events
    kept so late/resuming downstreams replay locally instead of each
    forcing a store round trip."""
    return max(64, config.env_int("EDL_TPU_RELAY_BUFFER", default))


class RelayWatch:
    """One downstream stream. Duck-types ``coord.store.Watch`` (get /
    progress_revision / cancel / cancelled / created_revision) but is
    deliberately not a subclass: ``__slots__`` plus a shared per-stream
    Condition keep a handle small enough that a million of them fit on
    one host (the --fleet simulation's in-proc cohort)."""

    __slots__ = ("_stream", "cond", "min_revision", "created_revision",
                 "_queue", "_cancelled")
    expiry_events = True

    def __init__(self, stream: "_Stream", min_revision: int,
                 created_revision: int):
        self._stream = stream
        self.cond = stream.cond  # SHARED per-stream Condition, not ours
        # resume fence: events at or below this were already in the
        # subscriber's hands before it attached — never re-deliver
        self.min_revision = min_revision
        self.created_revision = created_revision
        self._queue: list[WatchBatch] = []  # guarded-by: cond
        self._cancelled = False             # guarded-by: cond

    @property
    def prefix(self) -> str:
        return self._stream.prefix

    def get(self, timeout: float | None = None) -> WatchBatch | None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cond:
            while not self._queue and not self._cancelled:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self.cond.wait(remaining)
            if self._queue:
                return self._queue.pop(0)
            return None

    def progress_revision(self) -> int | None:
        with self.cond:
            if self._queue or self._cancelled:
                return None
            # the stream anchor came off upstream frames, which the
            # store commit-gates — safe to advertise downstream
            return self._stream.anchor

    def cancel(self) -> None:
        self._stream.detach(self)

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def __enter__(self) -> "RelayWatch":
        return self

    def __exit__(self, *exc) -> None:
        self.cancel()


class _Stream:
    """ONE upstream watch for one distinct prefix, re-multiplexed to
    every downstream subscribed to it."""

    def __init__(self, relay: "WatchRelay", prefix: str,
                 start_revision: int | None):
        self.relay = relay
        self.prefix = prefix
        self.cond = threading.Condition()
        self.subs: set[RelayWatch] = set()   # guarded-by: cond
        self.history: list = []              # guarded-by: cond
        self.closed = False                  # guarded-by: cond
        # Opened synchronously (ClientWatch blocks until the server
        # ack), so anchor/first_rev are real before the first attach
        # returns — "events after attach() returned" stays a guarantee
        # through the relay. via_relay=False: never watch through
        # yourself.
        self.upstream = relay._client.watch(
            prefix, start_revision=start_revision,
            heartbeat=relay.heartbeat, via_relay=False,
            on_resume=self._on_resume)
        self.anchor = self.upstream.created_revision  # guarded-by: cond
        base = start_revision if start_revision is not None else self.anchor
        self.first_rev = base + 1            # guarded-by: cond
        self._thread = threading.Thread(
            target=self._pump, daemon=True,
            name=f"relay-pump-{prefix or '/'}")
        self._thread.start()

    def _on_resume(self, revision: int) -> None:
        flight.record("relay_resume", prefix=self.prefix, revision=revision)
        self.relay._note_resume()
        log.info("relay stream %r resumed upstream at revision %d",
                 self.prefix, revision)

    # -- upstream side -------------------------------------------------------

    def _pump(self) -> None:
        up = self.upstream
        while True:
            batch = up.get(timeout=0.25)
            with self.cond:
                if self.closed:
                    return
            if batch is None:
                if up.cancelled:
                    return
                rev = up.progress_revision()
                if rev is not None:
                    with self.cond:
                        if rev > self.anchor:
                            self.anchor = rev
                continue
            self._deliver(batch)

    def _deliver(self, batch: WatchBatch) -> None:
        limit = self.relay.buffer
        fanned = 0
        with self.cond:
            if self.closed:
                return
            self.anchor = max(self.anchor, batch.revision)
            if batch.compacted:
                # upstream lost coverage: the relay's window is void
                # too — every downstream must resync via get_prefix
                self.history.clear()
                self.first_rev = batch.revision + 1
                resync = WatchBatch((), batch.revision, True)
                for sub in self.subs:
                    sub._queue.clear()
                    sub._queue.append(resync)
                self.cond.notify_all()
                return
            self.history.extend(batch.events)
            if len(self.history) > limit:
                drop = len(self.history) - limit
                self.first_rev = self.history[drop].revision
                del self.history[:drop]
            if batch.events:
                lo = batch.events[0].revision
                for sub in self.subs:
                    q = sub._queue
                    if len(q) >= _MAX_SUB_BATCHES:
                        # lagging downstream: collapse to a resync
                        q.clear()
                        q.append(WatchBatch((), batch.revision, True))
                        continue
                    if sub.min_revision < lo:
                        # fast path — the batch object is shared (it is
                        # frozen), so a 1M-subscriber fan-out appends one
                        # reference per sub, not one copy
                        q.append(batch)
                        fanned += len(batch.events)
                    else:
                        fit = tuple(ev for ev in batch.events
                                    if ev.revision > sub.min_revision)
                        if fit:
                            q.append(WatchBatch(fit, batch.revision))
                            fanned += len(fit)
            self.cond.notify_all()
        if fanned:
            self.relay._count_fanout(fanned)

    # -- downstream side -----------------------------------------------------

    def attach(self, start_revision: int | None) -> RelayWatch | None:
        """Subscribe; None when the stream closed under the caller
        (WatchRelay.attach retries with a fresh stream)."""
        with self.cond:
            if self.closed:
                return None
            anchor = self.anchor
            if start_revision is None:
                sub = RelayWatch(self, anchor, anchor)
            else:
                sub = RelayWatch(self, start_revision, anchor)
                if start_revision + 1 < self.first_rev:
                    # resume point predates the replay window: same
                    # explicit resync the store itself would answer
                    sub._queue.append(WatchBatch((), anchor, True))
                else:
                    replay = tuple(ev for ev in self.history
                                   if ev.revision > start_revision)
                    if replay:
                        sub._queue.append(WatchBatch(replay, anchor))
            self.subs.add(sub)
            return sub

    def detach(self, sub: RelayWatch) -> None:
        with self.cond:
            sub._cancelled = True
            self.subs.discard(sub)
            empty = not self.subs and not self.closed
            self.cond.notify_all()
        if empty:
            self.relay._maybe_close(self.prefix, self)

    def close(self) -> None:
        with self.cond:
            if self.closed:
                return
            self.closed = True
            for sub in self.subs:
                sub._cancelled = True
            self.subs.clear()
            self.cond.notify_all()
        self.upstream.cancel()


class WatchRelay:
    """The fan-out core (in-proc API; ``RelayServer`` puts it on the
    wire). ``attach(prefix, start_revision)`` returns a RelayWatch;
    distinct prefixes get one upstream stream each, shared by every
    subscriber of that prefix."""

    def __init__(self, upstream: str, buffer: int | None = None,
                 heartbeat: float = 2.0):
        self._client = StoreClient(upstream)
        self.buffer = buffer if buffer is not None else relay_buffer()
        self.heartbeat = heartbeat
        self._lock = threading.Lock()
        self._streams: dict[str, _Stream] = {}  # guarded-by: _lock
        self._fanout = 0                        # guarded-by: _lock
        self._resumes = 0                       # guarded-by: _lock
        self._closed = False                    # guarded-by: _lock
        self._obs = metrics.register_stats("relay", self.stats)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def attach(self, prefix: str = "",
               start_revision: int | None = None) -> RelayWatch:
        while True:
            with self._lock:
                if self._closed:
                    raise EdlStoreError("relay is closed")
                stream = self._streams.get(prefix)
            if stream is None:
                # dial upstream outside the relay lock (it can block on
                # a failing-over store); first creation wins
                stream = _Stream(  # lifecycle: long-lived(owned by _streams; relay.close or the losing-race branch closes it)
                    self, prefix, start_revision)
                with self._lock:
                    cur = None if self._closed \
                        else self._streams.setdefault(prefix, stream)
                if cur is not stream:
                    stream.close()
                    if cur is None:
                        raise EdlStoreError("relay is closed")
                    stream = cur
            sub = stream.attach(start_revision)
            if sub is not None:
                return sub
            with self._lock:  # stream closed under us: retry fresh
                if self._streams.get(prefix) is stream:
                    del self._streams[prefix]

    # Watch-provider shim: coord.server._Handler._serve_watch calls
    # ``store.watch(prefix, start_revision=...)`` — giving the relay the
    # same method lets RelayServer reuse the store server's watch loop
    # (ack, frame merging, heartbeats) verbatim.
    def watch(self, prefix: str = "",
              start_revision: int | None = None) -> RelayWatch:
        return self.attach(prefix, start_revision)

    def _maybe_close(self, prefix: str, stream: _Stream) -> None:
        with self._lock:
            with stream.cond:
                live = bool(stream.subs) or stream.closed
            if live or self._streams.get(prefix) is not stream:
                return
            del self._streams[prefix]
        stream.close()

    def _count_fanout(self, n: int) -> None:
        with self._lock:
            self._fanout += n

    def _note_resume(self) -> None:
        with self._lock:
            self._resumes += 1

    def stats(self) -> dict:
        with self._lock:
            streams = list(self._streams.values())
            fanout = self._fanout
            resumes = self._resumes
        downstreams = 0
        for st in streams:
            with st.cond:
                downstreams += len(st.subs)
        return {"relay_downstreams": downstreams,
                "relay_upstream_streams": len(streams),
                "relay_events_fanned_out": fanout,
                "relay_resumes": resumes}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            streams = list(self._streams.values())
            self._streams.clear()
        for st in streams:
            st.close()
        self._client.close()
        metrics.unregister(self._obs)


class _RelayHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        relay: WatchRelay = self.server.relay  # type: ignore[attr-defined]
        sock: socket.socket = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        from edl_tpu.coord.server import _Handler
        while True:
            try:
                req = wire.recv_msg(sock)
            except (wire.WireError, OSError):
                return
            trace.extract(req)  # pop the caller's span context
            op = req.get("op")
            if op == "watch":
                if relay.closed:
                    # drop the connection instead of sending a refusal:
                    # a refusal is permanent to ClientWatch, but a dying
                    # relay should look like a restart (reconnect+resume)
                    return
                # the store server's watch loop, fed by the relay core
                _Handler._serve_watch(relay, sock, req, self.server)
                return
            if op == "ping":
                resp = {"ok": True}
            elif op == "status":
                resp = {"ok": True, "role": "relay", "leader": None,
                        "term": 0, **relay.stats()}
            else:
                # non-watch ops proxy to the store through the shared
                # upstream client (failover/redirect handled there);
                # typed errors re-encode so the subtype survives the
                # extra hop
                try:
                    resp = relay._client._call(**req)
                except EdlStoreError as exc:
                    resp = {"ok": False,
                            "error": f"{type(exc).__name__}: {exc}"}
            try:
                wire.send_msg(sock, resp)
            except OSError:
                return


class _ThreadingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RelayServer:
    """Wire front of the relay: same framed protocol + watch semantics
    as StoreServer, so ``StoreClient`` works against it unchanged."""

    def __init__(self, upstream: str, port: int = 0, host: str = "0.0.0.0",
                 buffer: int | None = None, heartbeat: float = 2.0):
        self.relay = WatchRelay(upstream, buffer=buffer, heartbeat=heartbeat)
        self._server = _ThreadingServer((host, port), _RelayHandler)
        self._server.relay = self.relay  # type: ignore[attr-defined]
        self._server.active_watches = set()  # type: ignore[attr-defined]
        self._server.watch_lock = threading.Lock()  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "RelayServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="edl-relay-serve", daemon=True)
        self._thread.start()
        log.info("watch relay listening on :%d (upstream %s)", self.port,
                 self.relay._client._endpoint)
        return self

    def stop(self) -> None:
        # listener first: once it is gone, downstream reconnects bounce
        # (connection refused -> jittered backoff) instead of landing on
        # a relay that is mid-teardown
        self._server.shutdown()
        self._server.server_close()
        self.relay.close()
        with self._server.watch_lock:  # type: ignore[attr-defined]
            watches = list(self._server.active_watches)  # type: ignore[attr-defined]
        for watch in watches:
            watch.cancel()

    def __enter__(self) -> "RelayServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# --------------------------------------------------------------------------
# CLI: serve + stdlib-only selftest


def selftest(verbose: bool = True) -> int:
    """End-to-end relay invariants over real sockets: per-prefix
    upstream coalescing, fan-out delivery, the min_revision resume
    fence, compacted propagation for stale resume points, and the
    relay-death-equals-restart contract (kill the relay mid-stream,
    restart it, zero lost / zero duplicated events). Pure stdlib —
    asserted, per layers.toml."""
    from edl_tpu.coord.server import StoreServer

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        if verbose:
            print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    def drain(watch, want: int, timeout: float = 10.0) -> list:
        evs: list = []
        deadline = time.monotonic() + timeout
        while len(evs) < want and time.monotonic() < deadline:
            batch = watch.get(timeout=0.25)
            if batch is not None:
                evs.extend(batch.events)
        return evs

    srv = StoreServer(port=0, host="127.0.0.1").start()
    ep = f"127.0.0.1:{srv.port}"
    rs = RelayServer(ep, port=0, host="127.0.0.1").start()  # lifecycle: long-lived(selftest; stopped at the end, a failed check exits the process)
    relay_ep = f"127.0.0.1:{rs.port}"

    store = StoreClient(ep)
    downs = [StoreClient(relay_ep) for _ in range(3)]
    w_a1 = downs[0].watch("/a/", via_relay=False)
    w_a2 = downs[1].watch("/a/", via_relay=False)
    w_b = downs[2].watch("/b/", via_relay=False)

    revs = [store.put(f"/a/{i:03d}", str(i)) for i in range(10)]
    store.put("/b/x", "y")

    got1 = drain(w_a1, 10)
    got2 = drain(w_a2, 10)
    gotb = drain(w_b, 1)
    check([e.revision for e in got1] == revs,
          f"fan-out: downstream 1 saw all 10 events in order "
          f"(got {len(got1)})")
    check([e.revision for e in got2] == revs,
          "fan-out: downstream 2 saw the same stream")
    check(len(gotb) == 1 and gotb[0].key == "/b/x",
          "prefix isolation: /b/ watcher saw only its event")

    stats = rs.relay.stats()
    check(stats["relay_upstream_streams"] == 2,
          f"coalescing: 3 downstreams -> 2 upstream streams "
          f"(got {stats['relay_upstream_streams']})")
    check(stats["relay_downstreams"] == 3,
          f"stats: 3 downstreams tracked (got {stats['relay_downstreams']})")

    # resume fence: attach mid-history — nothing at or below the anchor
    # may be re-delivered
    anchor = revs[4]
    w_mid = StoreClient(relay_ep).watch("/a/", start_revision=anchor,
                                        via_relay=False)
    got_mid = drain(w_mid, 5)
    check([e.revision for e in got_mid] == revs[5:],
          f"min_revision fence: resume at rev {anchor} replays exactly "
          f"the 5 later events (got {[e.revision for e in got_mid]})")
    w_mid.cancel()

    # stale resume point (predates the relay stream's window): explicit
    # compacted resync, the same answer the store would give
    relay2 = WatchRelay(ep, buffer=64)
    sub = relay2.attach("/a/", start_revision=None)
    first_rev_gate = relay2.attach("/a/", start_revision=0)
    batch = first_rev_gate.get(timeout=5.0)
    check(batch is not None and batch.compacted,
          "stale resume point answers an explicit compacted resync")
    sub.cancel()
    first_rev_gate.cancel()
    relay2.close()

    # relay death == server restart: kill the relay mid-stream, write
    # through the gap, restart on the same port — downstreams reconnect
    # and resume by revision with zero lost / zero duplicated events
    port = rs.port
    rs.stop()
    revs2 = [store.put(f"/a/{i:03d}", str(i)) for i in range(10, 20)]
    rs = RelayServer(ep, port=port, host="127.0.0.1").start()  # lifecycle: long-lived(selftest respawn; stopped at the end)
    got1b = drain(w_a1, 10, timeout=20.0)
    got2b = drain(w_a2, 10, timeout=20.0)
    check([e.revision for e in got1b] == revs2,
          f"relay kill: downstream 1 resumed with zero lost/dup "
          f"(got {[e.revision for e in got1b]})")
    check([e.revision for e in got2b] == revs2,
          "relay kill: downstream 2 resumed identically")
    deadline = time.monotonic() + 20.0
    stats = rs.relay.stats()
    while stats["relay_downstreams"] < 3 and time.monotonic() < deadline:
        time.sleep(0.2)
        stats = rs.relay.stats()
    check(stats["relay_downstreams"] == 3
          and stats["relay_upstream_streams"] == 2,
          f"restarted relay re-coalesced all 3 downstreams onto 2 "
          f"upstream streams (got {stats['relay_downstreams']}/"
          f"{stats['relay_upstream_streams']})")

    for w in (w_a1, w_a2, w_b):
        w.cancel()
    for d in downs:
        d.close()
    store.close()
    rs.stop()
    srv.stop()

    import sys
    heavy = [m for m in ("jax", "jaxlib", "numpy", "flax", "optax")
             if m in sys.modules]
    check(not heavy,
          f"relay tier imports stay jax/numpy-free (saw {heavy})")

    if failures:
        print(f"relay selftest: {len(failures)} FAILED")
        return 1
    print("relay selftest: all checks passed")
    return 0


def serve(args) -> int:
    upstream = args.upstream or config.env_str(
        "EDL_TPU_STORE_ENDPOINTS", "")
    if not upstream:
        print("relay serve: --upstream or EDL_TPU_STORE_ENDPOINTS required")
        return 2
    server = RelayServer(  # lifecycle: long-lived(serve: runs until the process is killed)
        upstream, port=args.port, host=args.host,
        heartbeat=args.heartbeat)
    server.start()
    print(f"relay: listening on :{server.port} (upstream {upstream})",
          flush=True)
    threading.Event().wait()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description="edl_tpu watch relay tier")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("selftest", help="stdlib-only relay contract checks")
    ps = sub.add_parser("serve", help="run a relay server")
    ps.add_argument("--upstream", default="",
                    help="store endpoints (default EDL_TPU_STORE_ENDPOINTS)")
    ps.add_argument("--host", default="0.0.0.0")
    ps.add_argument("--port", type=int, default=2380)
    ps.add_argument("--heartbeat", type=float, default=2.0)
    args = parser.parse_args()
    if args.cmd == "selftest":
        return selftest()
    return serve(args)


if __name__ == "__main__":
    raise SystemExit(main())
