"""Store replication + sharding: the coordination plane loses its SPOF.

Until r17 every elastic mechanism (membership, leases, watch streams,
the scaler journal, the donor roster, distill discovery) hung off ONE
store process; the reference got HA for free from etcd's Raft (SURVEY
G3: lock/lease/txn semantics plus the split-brain "loser kills itself"
rule) and sharded discovery across replicas with a consistent-hash
REDIRECT protocol (SURVEY C3). This module is our version of both,
built from primitives the repo already has instead of a consensus
library:

**Replication (one shard group).** A group of ``ReplicaServer``
processes elect a leader with **quorum leases** — the candidate must
hold the lease-backed ``DistributedLock`` (coord/lock.py, unchanged
semantics) on a strict MAJORITY of the group's always-active election
sidecar stores. Two leaders cannot coexist (any two majorities
intersect), leadership is provably live only while a majority of those
leases renews (``held()`` is renewal-age-bounded — the fencing
discipline lock.py already documents), and a dead leader frees the
role within one TTL. Each election establishes a monotonically larger
**term**; replication messages carry it and followers reject lower
terms, so a deposed leader's appends bounce off any member of the new
majority — it can never again commit at majority, and on the first
rejection it steps down (the "loser kills itself" rule applied to
role) and marks itself **dirty** (the same rule applied to state: a
deposed leader rejoins via full snapshot install, discarding whatever
it applied past the committed point).

The replicated log is the store's OWN revision-stamped mutation
stream: the leader applies a write locally, then per-peer sender
threads ship ``events_since`` deltas (plus lease-grant side entries —
replicated PUTs already carry their lease id, so followers can rebuild
the lease->keys index on promotion) and the write is acknowledged to
the client only once a majority (leader included) has applied its
revision. Followers apply verbatim at the leader's revisions
(``InMemStore.apply_put/apply_delete`` — idempotent, so replays after
reconnect dedupe) and therefore serve **reads and watch fan-out**
locally: watches are resumable by revision, so a client that fails
over re-attaches with ``start_revision`` and misses nothing, or sees
an explicit ``compacted`` batch and resyncs — the contract
doc/design_coord.md already specifies, now surviving leader death.
Lease EXPIRY stays a leader-only decision (followers are passive,
store.set_passive): it reaches followers as ordinary replicated
DELETE events, and a fresh leader restarts every lease clock at
now+ttl — late expiry is safe, early expiry is not.

This is deliberately NOT Raft: no persistent voted-for state, no
log-divergence reconciliation (dirty nodes take a snapshot instead),
and commit durability is majority-memory, not majority-disk (the
native WAL daemon covers single-node durability). The weaker story is
documented in doc/parity.md; the guarantees the elastic machinery
actually consumes — zero lost acked events across failover, fenced
writes, bounded failover time — are real and chaos-tested
(``python -m edl_tpu.coord.replication dryrun``).

**Sharding (many groups).** Registry prefixes shard across replica
groups with the existing ``ConsistentHash`` ring over group names.
``shard_key`` maps ``/{root}/{service}/...`` to its first two path
segments, so one service's subtree — records, watches, lease-guarded
registrations — lands wholly in one group. A server that does not own
a key answers a structured REDIRECT naming the owning group's
endpoints (wire.py), ``StoreClient`` follows it (bounded hops), and
``ShardedStoreClient`` routes directly, materializing leases lazily in
the owner group of the first key that uses them.
"""

from __future__ import annotations

import argparse
import json
import random
import socket
import sys
import threading
import time
from collections import deque

from edl_tpu.coord import wire
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.consistent_hash import ConsistentHash
from edl_tpu.coord.lock import DistributedLock
from edl_tpu.coord.store import Event, InMemStore, Record, Store, Watch
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import recorder as flight
from edl_tpu.utils import config
from edl_tpu.utils.backoff import Backoff
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.logging import get_logger
from edl_tpu.utils.net import split_endpoint

log = get_logger("edl_tpu.coord.replication")

_ELECTION_KEY = "!elect/leader"
_TERM_KEY = "!elect/term"
_WRITE_OPS = frozenset({
    "put", "delete", "delete_prefix", "put_if_absent", "cas",
    "lease_grant", "lease_keepalive", "lease_revoke",
})
_KEY_OPS = frozenset({"put", "get", "delete", "put_if_absent", "cas"})
_PREFIX_OPS = frozenset({"get_prefix", "delete_prefix", "events_since",
                         "watch"})
_SIDE_LOG_MAX = 4096
# leader-side log compaction: once every peer has acked past the floor,
# compact history up to it every _COMPACT_EVERY revisions, keeping a
# _COMPACT_KEEP-event resume cushion for late watch resumers
_COMPACT_EVERY = 2048
_COMPACT_KEEP = 512


def election_ttl_default() -> float:
    """Quorum-lease TTL (seconds): the failover detection horizon — a
    dead leader's locks free within one TTL (EDL_TPU_STORE_ELECTION_TTL)."""
    return max(0.1, config.env_float("EDL_TPU_STORE_ELECTION_TTL", 3.0))


# --------------------------------------------------------------------------
# sharding: key -> group routing


def shard_key(key: str) -> str:
    """The unit of placement: the first two path segments, so one
    service's records/watches/leases co-locate in one replica group
    (``/edl/teachers/nodes/h:1`` -> ``/edl/teachers``)."""
    parts = [p for p in key.split("/") if p]
    if not parts:
        return key
    return "/" + "/".join(parts[:2])


def parse_topology(spec: str, shards: int | None = None
                   ) -> dict[str, list[str]]:
    """Topology from an endpoint spec string.

    - ``"h0:p,h1:p,h2:p"`` — one replica group (name ``shard0``) —
      unless ``EDL_TPU_STORE_SHARDS`` (or ``shards``) asks for k>1
      groups, in which case the flat list is chunked contiguously;
    - ``"h0:p,h1:p;h3:p,h4:p"`` — ``;`` separates groups
      (auto-named ``shard0..shardN``);
    - ``"users=h0:p,h1:p;jobs=h3:p"`` — explicit group names (names are
      the hash-ring identities: keep them stable across resizes or
      every prefix remaps).
    """
    chunks = [c for c in spec.split(";") if c.strip()]
    if len(chunks) == 1 and "=" not in chunks[0]:
        eps = [e.strip() for e in chunks[0].split(",") if e.strip()]
        k = shards if shards is not None \
            else config.env_int("EDL_TPU_STORE_SHARDS", 1)
        if k <= 1 or len(eps) < k:
            return {"shard0": eps}
        per, extra = divmod(len(eps), k)
        groups, at = {}, 0
        for i in range(k):
            size = per + (1 if i < extra else 0)
            groups[f"shard{i}"] = eps[at:at + size]
            at += size
        return groups
    groups = {}
    for i, chunk in enumerate(chunks):
        if "=" in chunk:
            name, _, rest = chunk.partition("=")
        else:
            name, rest = f"shard{i}", chunk
        groups[name.strip()] = [e.strip() for e in rest.split(",")
                                if e.strip()]
    return groups


def topology_spec(groups: dict[str, list[str]]) -> str:
    return ";".join(f"{g}={','.join(eps)}" for g, eps in groups.items())


class ShardRouter:
    """Key/prefix -> owning replica group, over the copy-on-write
    consistent-hash ring (coord/consistent_hash.py)."""

    SPANS = "!spans"  # sentinel: prefix too short to pin one shard

    def __init__(self, groups: dict[str, list[str]]):
        if not groups:
            raise EdlStoreError("empty shard topology")
        self.groups = {g: list(eps) for g, eps in groups.items()}
        self._single = next(iter(groups)) if len(groups) == 1 else None
        self._ring = None if self._single else ConsistentHash(list(groups))

    def owner(self, key: str) -> str:
        if self._single is not None:
            return self._single
        return self._ring.lookup(shard_key(key))

    def owner_of_prefix(self, prefix: str) -> str:
        """Owning group for a prefix, or ``SPANS`` when the prefix pins
        fewer than two path segments (it could cover several shards)."""
        if self._single is not None:
            return self._single
        if len([p for p in prefix.split("/") if p]) < 2:
            return self.SPANS
        return self._ring.lookup(shard_key(prefix))

    def endpoints(self, group: str) -> list[str]:
        return self.groups[group]

    def route(self, op: str, req: dict) -> str | None:
        """Owning group for a request: a group name, ``SPANS``, or None
        for ops with no placement (lease ops are leader-local to
        whichever group the client routed them to)."""
        if op in _KEY_OPS:
            return self.owner(req.get("key", ""))
        if op in _PREFIX_OPS:
            return self.owner_of_prefix(req.get("prefix", ""))
        return None


# --------------------------------------------------------------------------
# quorum lease: leadership = DistributedLock held on a majority


class _ElectClient(StoreClient):
    """StoreClient whose every request routes to the peer's ALWAYS-ACTIVE
    election sidecar store (``elect_space`` flag, wire.py) — the
    election substrate must keep granting/expiring leases while the
    data store is a passive follower. Short budgets: an unreachable
    peer must fail a campaign round fast, not after the data client's
    patient 30-round schedule."""

    def __init__(self, node: "ReplicaNode", endpoint: str, ttl: float):
        self._node = node
        self._peer = endpoint
        super().__init__(endpoint, timeout=max(0.2, min(1.0, ttl / 2.0)),
                         connect_retries=1, retry_interval=0.05)

    def _call(self, **req) -> dict:
        if self._node._blocked(self._peer):
            raise EdlStoreError("partitioned (chaos hook)")
        req["elect_space"] = True
        return super()._call(**req)


class QuorumLease:
    """Leadership as a majority of lease-backed locks.

    One ``DistributedLock`` per group member (the member's own sidecar
    in-process, peers over ``_ElectClient``); acquisition wins only
    with a strict majority and releases partial wins immediately.
    ``held()`` is the fencing check: True only while a majority of the
    underlying leases is PROVABLY live (each lock bounds its answer by
    its last confirmed renewal's age — coord/lock.py)."""

    def __init__(self, node: "ReplicaNode"):
        self._node = node
        self.majority = node.majority
        self.locks: list[DistributedLock] = []
        for ep in node.group_endpoints:
            store = node.elect if ep == node.endpoint \
                else node._elect_client(ep)
            self.locks.append(DistributedLock(
                store, _ELECTION_KEY, node.endpoint,
                ttl=node.election_ttl))

    def try_acquire(self) -> bool:
        wins = 0
        for lock in self.locks:
            try:
                if lock.try_acquire():
                    wins += 1
            except (EdlStoreError, ConnectionError, OSError):
                pass  # unreachable member counts as a lost vote
        if wins >= self.majority:
            return True
        self.release()
        return False

    def held(self) -> bool:
        return sum(1 for lock in self.locks if lock.held()) >= self.majority

    def release(self) -> None:
        for lock in self.locks:
            try:
                lock.release()
            except (EdlStoreError, ConnectionError, OSError):
                pass

    def abandon(self) -> None:
        """Crash simulation: stop keepalives WITHOUT revoking, so the
        role frees only when the TTLs run out — chaos tests pay the
        real failover price."""
        for lock in self.locks:
            lock.abandon()


# --------------------------------------------------------------------------
# the replica node


class ReplicaNode:
    """Replication/routing brain of one store replica.

    Owns the replicated data store (``self.store``, passive while
    follower), the election sidecar (``self.elect``, always active),
    the elector thread and one sender thread per peer. Plugged into
    ``StoreServer`` via ``intercept`` (coord/server.py calls it for
    every request before local dispatch).
    """

    def __init__(self, endpoint: str, group_endpoints: list[str], *,
                 group: str = "shard0",
                 topology: dict[str, list[str]] | None = None,
                 store: InMemStore | None = None,
                 election_ttl: float | None = None,
                 heartbeat: float | None = None,
                 commit_timeout: float = 5.0,
                 rng: random.Random | None = None):
        if endpoint not in group_endpoints:
            raise EdlStoreError(
                f"replica endpoint {endpoint!r} missing from its own "
                f"group {group_endpoints!r}")
        self.endpoint = endpoint
        self.group = group
        self.group_endpoints = list(group_endpoints)
        self.peers = [e for e in group_endpoints if e != endpoint]
        self.majority = len(self.group_endpoints) // 2 + 1
        self.store = store or InMemStore()
        self.elect = InMemStore()
        self.router = ShardRouter(topology) \
            if topology and len(topology) > 1 else None
        self.election_ttl = election_ttl if election_ttl is not None \
            else election_ttl_default()
        self.heartbeat = heartbeat if heartbeat is not None \
            else max(0.05, min(0.25, self.election_ttl / 8.0))
        self.commit_timeout = commit_timeout
        self._rng = rng or random.Random()

        self._state_lock = threading.Lock()
        self._role = "follower"            # guarded-by: _state_lock
        self._term = 0                     # guarded-by: _state_lock
        self._leader_endpoint: str | None = None  # guarded-by: _state_lock
        self._last_leader_contact = 0.0    # guarded-by: _state_lock
        # deposed-leader marker: state past the commit point may
        # diverge — rejoin via snapshot, not incremental append
        self._dirty = False                # guarded-by: _state_lock

        self._commit_cond = threading.Condition()
        self._commit_rev = 0               # guarded-by: _commit_cond
        self._match: dict[str, int] = {}   # guarded-by: _commit_cond

        self._side_lock = threading.Lock()
        # lease-grant/revoke side entries: (seq, pos, wire entry) — the
        # event log carries everything else (PUT events carry lease ids)
        self._side: deque = deque(maxlen=_SIDE_LOG_MAX)  # guarded-by: _side_lock
        self._side_seq = 0                 # guarded-by: _side_lock

        self._wake_cond = threading.Condition()
        self._pending: dict[str, bool] = {p: False for p in self.peers}  # guarded-by: _wake_cond

        self._elect_clients: dict[str, _ElectClient] = {}
        # Chaos partition hook: False = healthy, True = severed from ALL
        # peers (the asymmetric partition: clients still reach this
        # node's server socket, but it cannot reach quorum), or a
        # frozenset of peer endpoints to sever selectively. Inbound
        # peer traffic from a severed endpoint is refused too, so a
        # partition is symmetric per-link.
        self._partition: frozenset[str] | bool = False
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # election-churn counters (the obs registry's replica view; the
        # flight recorder keeps the per-transition event detail)
        self._elections_won = 0            # guarded-by: _state_lock
        self._step_downs = 0               # guarded-by: _state_lock
        self._snapshot_installs = 0        # guarded-by: _state_lock
        self._delta_installs = 0           # guarded-by: _state_lock
        # leader-side log compaction floor (last revision compacted to)
        self._compact_floor = 0            # guarded-by: _commit_cond
        self._obs = obs_metrics.register_stats("replica", self.stats)
        self.store.set_passive(True)
        # Commit-gated watch fan-out: a replicated store's watchers
        # (local AND wire-served, leader AND follower) only ever see
        # events at or below the majority-committed revision — a doomed
        # leader's uncommitted suffix is buffered, then discarded by the
        # snapshot rejoin, so no watcher can observe revisions a new
        # reign will reuse (closes the r18 branch anomaly;
        # doc/design_coord.md).
        self.store.set_fanout_gate(True)
        self.quorum = QuorumLease(self)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ReplicaNode":
        elector = threading.Thread(target=self._elector, daemon=True,
                                   name=f"repl-elect-{self.endpoint}")
        self._threads = [elector]
        for peer in self.peers:
            t = threading.Thread(target=self._sender_loop, args=(peer,),
                                 daemon=True,
                                 name=f"repl-send-{self.endpoint}->{peer}")
            self._threads.append(t)
        for t in self._threads:
            t.start()
        return self

    def stats(self) -> dict:
        """Replica counters as a dict view (registered into the obs
        registry at construction): role/term plus election churn —
        the numbers the HA bench and a scrape read identically."""
        with self._state_lock:
            return {"role": self._role, "term": self._term,
                    "dirty": self._dirty,
                    "is_leader": self._role == "leader",
                    "elections_won": self._elections_won,
                    "step_downs": self._step_downs,
                    "snapshot_installs": self._snapshot_installs,
                    "delta_installs": self._delta_installs,
                    "peers": len(self.peers)}

    def stop(self, graceful: bool = True) -> None:
        """Graceful stop resigns (successors campaign immediately);
        ``graceful=False`` simulates a crash — locks stay until TTL."""
        obs_metrics.unregister(self._obs)
        self._stop.set()
        with self._wake_cond:
            self._wake_cond.notify_all()
        with self._commit_cond:
            self._commit_cond.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        if graceful:
            self.quorum.release()
        else:
            self.quorum.abandon()
        for client in self._elect_clients.values():
            client.close()

    def kill(self) -> None:
        self.stop(graceful=False)

    # -- chaos partition hook ------------------------------------------------

    def set_partition(self, peers: bool | list[str] | None) -> None:
        """Sever (or heal, with None/False) this node's peer links:
        True drops traffic to/from every peer, a list severs only those
        endpoints. Client connections to this node's own server socket
        are untouched — combining ``set_partition(True)`` on a leader
        with a client pinned to it is the asymmetric partition drill
        (reachable deposed leader, unreachable quorum)."""
        if peers is None or peers is False:
            self._partition = False
        elif peers is True:
            self._partition = True
        else:
            self._partition = frozenset(peers)

    def _blocked(self, peer: str | None) -> bool:
        part = self._partition
        if part is False:
            return False
        if part is True:
            return True
        return peer is not None and peer in part

    # Legacy chaos hook spelling (tests set it directly): truthiness
    # maps onto the all-peers partition.
    @property
    def _partitioned(self) -> bool:
        return bool(self._partition)

    @_partitioned.setter
    def _partitioned(self, value: bool) -> None:
        self.set_partition(bool(value))

    def sweep(self) -> None:
        """Called by the hosting StoreServer's sweeper: the election
        sidecar expires leases even while the data store is passive.
        A leader also advances the commit gate here — the net that
        releases lease-expiry DELETEs (and single-replica groups, which
        have no sender acks) to watchers on a bounded cadence."""
        self.elect.sweep()
        if self.role() == "leader":
            self._advance_fanout()

    def _elect_client(self, endpoint: str) -> _ElectClient:
        client = self._elect_clients.get(endpoint)
        if client is None:
            client = _ElectClient(self, endpoint, self.election_ttl)
            self._elect_clients[endpoint] = client
        return client

    # -- role/introspection -------------------------------------------------

    def role(self) -> str:
        with self._state_lock:
            return self._role

    def term(self) -> int:
        with self._state_lock:
            return self._term

    def leader_endpoint(self) -> str | None:
        with self._state_lock:
            if self._role == "leader":
                return self.endpoint
            # a hint older than the election horizon is worse than no
            # hint: during failover it names the DEAD leader and sends
            # clients chasing a corpse instead of backing off for the
            # new one
            if time.monotonic() - self._last_leader_contact \
                    > self.election_ttl:
                return None
            return self._leader_endpoint

    def is_leader(self) -> bool:
        """Lease-fenced: role alone is a hint; the quorum lease must be
        provably live. Consulted before every acknowledged write."""
        return self.role() == "leader" and self.quorum.held()

    def status_doc(self) -> dict:
        with self._state_lock:
            role, term, dirty = self._role, self._term, self._dirty
        leader = self.leader_endpoint()
        return {"ok": True, "role": role, "term": term, "leader": leader,
                "revision": self.store.current_revision,
                "group": self.group, "endpoints": self.group_endpoints,
                "dirty": dirty, "commit": self.commit_revision()}

    def commit_revision(self) -> int:
        with self._commit_cond:
            return self._commit_rev

    # -- election -----------------------------------------------------------

    def _elector(self) -> None:
        campaign_backoff = Backoff(base=self.election_ttl / 4.0,
                                   max_delay=self.election_ttl,
                                   rng=self._rng)
        while not self._stop.is_set():
            if self.role() == "leader":
                if not self.quorum.held():
                    self.step_down("quorum lease lost")
                elif self._stop.wait(max(0.02, self.election_ttl / 8.0)):
                    return
                continue
            with self._state_lock:
                age = time.monotonic() - self._last_leader_contact
            if age < self.election_ttl:
                # a live leader is appending/heartbeating — no campaign
                if self._stop.wait(max(0.02, self.election_ttl / 4.0)):
                    return
                continue
            if self._peer_ahead():
                # election restriction: a reachable peer with a higher
                # revision holds committed state we might not — defer,
                # let it win (combined with majority-ack writes this is
                # what preserves acked events across leader death)
                if campaign_backoff.sleep(self._stop):
                    return
                continue
            if self.quorum.try_acquire():
                self._become_leader()
                campaign_backoff.reset()
            elif campaign_backoff.sleep(self._stop):
                return

    def _peer_ahead(self) -> bool:
        mine = self.store.current_revision
        for peer in self.peers:
            try:
                resp = self._peer_call(peer, {"op": "status"},
                                       timeout=max(0.2, self.election_ttl / 4))
            except (EdlStoreError, OSError, wire.WireError):
                continue
            if int(resp.get("revision", 0)) > mine \
                    and not resp.get("dirty"):
                return True
        return False

    def _become_leader(self) -> None:
        # Establish the fencing term: strictly above every term any
        # reachable member has seen. Persisted in the election sidecars
        # so the NEXT winner reads past this reign even if we crash.
        terms = [self._read_term(self.elect)]
        with self._state_lock:
            terms.append(self._term)
        for peer in self.peers:
            try:
                terms.append(self._read_term(self._elect_client(peer)))
            except (EdlStoreError, ConnectionError, OSError):
                pass
        new_term = max(terms) + 1
        try:
            self.elect.put(_TERM_KEY, str(new_term))
        except EdlStoreError:
            pass
        for peer in self.peers:
            try:
                self._elect_client(peer).put(_TERM_KEY, str(new_term))
            except (EdlStoreError, ConnectionError, OSError):
                pass
        with self._state_lock:
            self._role = "leader"
            self._term = new_term
            self._leader_endpoint = self.endpoint
            self._last_leader_contact = time.monotonic()
            self._dirty = False
            self._elections_won += 1
        flight.record("election", replica=self.endpoint, group=self.group,
                      term=new_term, won=True)
        # active mode: resume lease-expiry duty; every lease clock
        # restarts at now+ttl (late expiry is safe, early is not)
        self.store.set_passive(False)
        # a new reign's local log IS the committed baseline (divergent
        # peers rejoin via snapshot): open the fan-out gate up to it
        self.store.release_fanout(self.store.current_revision)
        with self._commit_cond:
            self._match = {}
            self._recompute_commit_locked()
        self.notify_senders()
        log.info("replica %s is LEADER of %s (term %d, revision %d)",
                 self.endpoint, self.group, new_term,
                 self.store.current_revision)

    @staticmethod
    def _read_term(store: Store) -> int:
        rec = store.get(_TERM_KEY)
        try:
            return int(rec.value) if rec is not None else 0
        except ValueError:
            return 0

    def step_down(self, reason: str, new_term: int | None = None) -> None:
        with self._state_lock:
            was_leader = self._role == "leader"
            self._role = "follower"
            if new_term is not None and new_term > self._term:
                self._term = new_term
            if was_leader:
                self._leader_endpoint = None
                self._dirty = True
                self._step_downs += 1
            term = self._term
        if was_leader:
            self.store.set_passive(True)
            flight.record("failover", replica=self.endpoint,
                          group=self.group, term=term, reason=reason)
            log.warning("replica %s deposed (%s) — dirty until snapshot "
                        "rejoin", self.endpoint, reason)
        self.quorum.release()
        with self._commit_cond:
            self._commit_cond.notify_all()  # waiters re-check role, fail fast

    # -- leader: log shipping ----------------------------------------------

    def _append_side(self, entry: list) -> None:
        with self._side_lock:
            self._side_seq += 1
            self._side.append((self._side_seq, self.store.current_revision,
                               entry))

    def _entries_since(self, rev: int, side_seq: int):
        """(entries, side_seq') covering everything a follower at
        ``rev`` is missing, or None when the event history no longer
        reaches back that far (caller ships a snapshot instead)."""
        evs, _cur, compacted = self.store.events_since(rev)
        if compacted:
            return None
        entries: list[tuple] = []
        for ev in evs:
            lease = 0
            if ev.type == "PUT":
                rec = self.store.get(ev.key)
                if rec is not None and rec.revision == ev.revision:
                    lease = rec.lease
            entries.append(((ev.revision, 0),
                            ["EV", ev.type, ev.key, ev.value, ev.revision,
                             lease]))
        new_seq = side_seq
        with self._side_lock:
            for seq, pos, entry in self._side:
                if seq > side_seq:
                    entries.append(((pos, 1), entry))
                    new_seq = max(new_seq, seq)
        entries.sort(key=lambda pair: pair[0])
        return [e for _, e in entries], new_seq

    def notify_senders(self) -> None:
        with self._wake_cond:
            for peer in self._pending:
                self._pending[peer] = True
            self._wake_cond.notify_all()

    def _update_match(self, peer: str, rev: int) -> None:
        with self._commit_cond:
            self._match[peer] = max(self._match.get(peer, 0), rev)
            self._recompute_commit_locked()
            commit = self._commit_rev
            # log-compaction floor: the lowest revision ANY peer has
            # acked — history below it only serves late watch resumers
            floor = min((self._match.get(p, 0) for p in self.peers),
                        default=commit)
            floor = min(floor, commit)
            compact_to = 0
            if floor - self._compact_floor >= _COMPACT_EVERY:
                self._compact_floor = compact_to = floor
        # commit advanced (or held): release watch fan-out up to it —
        # outside the condition so the lock order stays commit_cond ->
        # store lock in one direction only
        self.store.release_fanout(commit)
        if compact_to:
            dropped = self.store.compact(compact_to, keep=_COMPACT_KEEP)
            if dropped:
                log.debug("leader %s compacted %d events (<= rev %d)",
                          self.endpoint, dropped, compact_to)

    def _advance_fanout(self) -> None:
        """Recompute the commit point and release watch fan-out to it."""
        with self._commit_cond:
            self._recompute_commit_locked()
            commit = self._commit_rev
        self.store.release_fanout(commit)

    def _recompute_commit_locked(self) -> None:  # holds-lock: _commit_cond
        revs = [self.store.current_revision]
        revs += [self._match.get(p, -1) for p in self.peers]
        revs.sort(reverse=True)
        commit = revs[self.majority - 1]
        if commit > self._commit_rev:
            self._commit_rev = commit
            self._commit_cond.notify_all()

    def _wait_commit(self, rev: int) -> bool:
        deadline = time.monotonic() + self.commit_timeout
        with self._commit_cond:
            self._recompute_commit_locked()
            while self._commit_rev < rev:
                if self._stop.is_set() or not self.is_leader():
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._commit_cond.wait(min(remaining, 0.1))
            return True

    def _sender_loop(self, peer: str) -> None:
        sock: socket.socket | None = None
        peer_rev: int | None = None  # None: probe before next append
        side_seq = 0
        last_send = 0.0
        backoff = Backoff(base=max(0.02, self.heartbeat / 2.0),
                          max_delay=min(1.0, self.election_ttl),
                          rng=self._rng)

        def _drop() -> None:
            nonlocal sock, peer_rev
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            sock, peer_rev = None, None

        while not self._stop.is_set():
            with self._wake_cond:
                if not self._pending.get(peer):
                    self._wake_cond.wait(self.heartbeat)
                self._pending[peer] = False
            if self._stop.is_set():
                break
            if self.role() != "leader" or self._blocked(peer):
                _drop()
                continue
            try:
                if sock is None:
                    sock = socket.create_connection(
                        split_endpoint(peer),
                        timeout=max(0.5, self.election_ttl))
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                term = self.term()
                if peer_rev is None:
                    resp = self._roundtrip(sock, {
                        "op": "repl_probe", "term": term,
                        "leader": self.endpoint})
                    if self._check_stale(resp):
                        _drop()
                        continue
                    if resp.get("dirty"):
                        peer_rev, side_seq = self._send_snapshot(sock, term)
                    else:
                        peer_rev = int(resp["revision"])
                        side_seq = 0
                    self._update_match(peer, peer_rev)
                got = self._entries_since(peer_rev, side_seq)
                if got is None:
                    peer_rev, side_seq = self._send_snapshot(sock, term)
                    self._update_match(peer, peer_rev)
                else:
                    entries, new_seq = got
                    due = time.monotonic() - last_send >= self.heartbeat
                    if entries or due:
                        resp = self._roundtrip(sock, {
                            "op": "repl_append", "term": term,
                            "leader": self.endpoint,
                            "commit": self.commit_revision(),
                            "entries": entries})
                        if self._check_stale(resp):
                            _drop()
                            continue
                        if not resp.get("ok"):
                            raise EdlStoreError(str(resp.get("error")))
                        peer_rev = int(resp["revision"])
                        side_seq = new_seq
                        last_send = time.monotonic()
                        self._update_match(peer, peer_rev)
                backoff.reset()
            except (OSError, wire.WireError, EdlStoreError, KeyError,
                    TypeError, ValueError) as exc:
                log.debug("sender %s->%s error: %s", self.endpoint, peer,
                          exc)
                _drop()
                if backoff.sleep(self._stop):
                    return

    def _send_snapshot(self, sock: socket.socket, term: int
                       ) -> tuple[int, int]:
        """Ship catch-up state: delta-compressed against the peer's
        digest when it answers one (only divergent/missing records
        cross the wire — fast rejoin for a briefly-dirty ex-leader
        whose keyspace is 99% identical), full state otherwise."""
        msg: dict = {"op": "repl_snapshot", "term": term,
                     "leader": self.endpoint}
        revision = None
        try:
            dig = self._roundtrip(sock, {
                "op": "repl_digest", "term": term, "leader": self.endpoint})
            if self._check_stale(dig):
                raise EdlStoreError("deposed during digest exchange")
            if dig.get("ok") and dig.get("digest") is not None:
                delta = self.store.snapshot_delta(dig["digest"])
                msg["delta"] = delta
                revision = int(delta["revision"])
        except (KeyError, TypeError, ValueError):
            pass  # malformed digest: fall through to a full snapshot
        if revision is None:
            state = self.store.snapshot_state()
            msg["state"] = state
            revision = int(state["revision"])
        resp = self._roundtrip(sock, msg)
        if self._check_stale(resp):
            raise EdlStoreError("deposed during snapshot install")
        if not resp.get("ok"):
            raise EdlStoreError(str(resp.get("error")))
        with self._side_lock:
            seq = self._side_seq
        return revision, seq

    def _check_stale(self, resp: dict) -> bool:
        if resp.get("stale_term"):
            self.step_down("rejected by higher term "
                           f"{resp.get('term')}",
                           new_term=int(resp.get("term") or 0))
            return True
        return False

    @staticmethod
    def _roundtrip(sock: socket.socket, msg: dict) -> dict:
        wire.send_msg(sock, msg)
        return wire.recv_msg(sock)

    def _peer_call(self, endpoint: str, msg: dict, timeout: float) -> dict:
        if self._blocked(endpoint):
            raise EdlStoreError("partitioned (chaos hook)")
        sock = socket.create_connection(split_endpoint(endpoint),
                                        timeout=timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return self._roundtrip(sock, msg)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    # -- follower: applying the log -----------------------------------------

    def _accept_leader(self, term: int, leader: str) -> dict | None:
        """Term gate for every peer message; None accepts, a dict is
        the stale-term rejection to send back (the fencing half of the
        split-brain rule — the deposed leader reads it and kills its
        own leadership)."""
        step_down_reason = None
        with self._state_lock:
            if term < self._term or (term == self._term
                                     and self._role == "leader"
                                     and leader != self.endpoint):
                return {"ok": False, "stale_term": True, "term": self._term,
                        "error": f"stale term {term} < {self._term}"}
            if self._role == "leader" and leader != self.endpoint:
                step_down_reason = f"saw leader {leader} at term {term}"
            else:
                self._term = max(self._term, term)
                self._leader_endpoint = leader
                self._last_leader_contact = time.monotonic()
        if step_down_reason is not None:
            self.step_down(step_down_reason, new_term=term)
            with self._state_lock:
                self._leader_endpoint = leader
                self._last_leader_contact = time.monotonic()
        return None

    def _handle_probe(self, req: dict) -> dict:
        rejection = self._accept_leader(int(req.get("term", 0)),
                                        str(req.get("leader", "")))
        if rejection is not None:
            return rejection
        with self._state_lock:
            dirty = self._dirty
        return {"ok": True, "revision": self.store.current_revision,
                "dirty": dirty, "term": self.term()}

    def _handle_append(self, req: dict) -> dict:
        rejection = self._accept_leader(int(req.get("term", 0)),
                                        str(req.get("leader", "")))
        if rejection is not None:
            return rejection
        for entry in req.get("entries", ()):
            kind = entry[0]
            if kind == "EV":
                _, typ, key, value, rev, lease = entry
                if typ == "PUT":
                    self.store.apply_put(key, value, int(rev),
                                         int(lease or 0))
                else:
                    self.store.apply_delete(key, value, int(rev))
            elif kind == "LEASE":
                self.store.apply_lease(int(entry[1]), float(entry[2]))
            elif kind == "LEASE_GONE":
                self.store.apply_lease_gone(int(entry[1]))
        # follower-side commit gate: the leader's append carries its
        # commit point; everything at or below it is safe to fan out
        # (release_fanout clamps to what was actually applied here)
        self.store.release_fanout(int(req.get("commit", 0)))
        return {"ok": True, "revision": self.store.current_revision,
                "term": self.term()}

    def _handle_digest(self, req: dict) -> dict:
        rejection = self._accept_leader(int(req.get("term", 0)),
                                        str(req.get("leader", "")))
        if rejection is not None:
            return rejection
        return {"ok": True, "digest": self.store.state_digest(),
                "term": self.term()}

    def _handle_snapshot(self, req: dict) -> dict:
        rejection = self._accept_leader(int(req.get("term", 0)),
                                        str(req.get("leader", "")))
        if rejection is not None:
            return rejection
        delta = req.get("delta")
        if delta is not None:
            self.store.install_snapshot_delta(delta)
        else:
            self.store.install_snapshot(req.get("state") or {})
        with self._state_lock:
            self._dirty = False
            self._snapshot_installs += 1
            if delta is not None:
                self._delta_installs += 1
        flight.record("snapshot_install", replica=self.endpoint,
                      group=self.group, delta=delta is not None,
                      revision=self.store.current_revision)
        log.info("replica %s installed %s snapshot at revision %d",
                 self.endpoint, "delta" if delta is not None else "full",
                 self.store.current_revision)
        return {"ok": True, "revision": self.store.current_revision,
                "term": self.term()}

    # -- the server hook ----------------------------------------------------

    def intercept(self, req: dict) -> dict | None:
        """Routing for one request; None means 'serve from the local
        store' (reads and watches on ANY role — followers serve watch
        fan-out — and everything on a clean leader)."""
        from edl_tpu.coord.server import _Handler
        op = req.get("op")
        if req.get("elect_space"):
            sub = {k: v for k, v in req.items() if k != "elect_space"}
            if op == "watch" or op.startswith("repl_"):
                return {"ok": False,
                        "error": f"op {op!r} unsupported in elect space"}
            return _Handler._dispatch(self.elect, sub)
        if op in ("repl_probe", "repl_append", "repl_digest",
                  "repl_snapshot"):
            if self._blocked(str(req.get("leader") or "") or None):
                return {"ok": False, "error": "partitioned (chaos hook)"}
            if op == "repl_probe":
                return self._handle_probe(req)
            if op == "repl_append":
                return self._handle_append(req)
            if op == "repl_digest":
                return self._handle_digest(req)
            return self._handle_snapshot(req)
        if op == "status":
            return self.status_doc()
        if self.router is not None:
            owner = self.router.route(op, req)
            if owner == ShardRouter.SPANS:
                return {"ok": False, "error":
                        "EdlStoreError: prefix spans shard groups — "
                        "scope reads/watches to /{root}/{service}/ in "
                        "a sharded topology"}
            if owner is not None and owner != self.group:
                return {"ok": False, "redirect": True, "group": owner,
                        "endpoints": self.router.endpoints(owner),
                        "error": f"key owned by shard group {owner!r}"}
        if op in _WRITE_OPS:
            return self._leader_write(req)
        return None  # reads/watch: local store, any role

    def _leader_write(self, req: dict) -> dict:
        from edl_tpu.coord.server import _Handler
        if not self.is_leader():
            return {"ok": False, "not_leader": True,
                    "leader": self.leader_endpoint(),
                    "error": "EdlStoreError: not the leader"}
        op = req.get("op")
        resp = _Handler._dispatch(self.store, req)
        if not resp.get("ok"):
            return resp
        if op == "lease_grant":
            self._append_side(["LEASE", resp["lease"], float(req["ttl"])])
            self.notify_senders()
            return resp  # grant metadata: majority wait not required
        if op == "lease_keepalive":
            return resp  # leader-local; promotion re-bases deadlines
        if op == "lease_revoke":
            self._append_side(["LEASE_GONE", req["lease"]])
        rev = self.store.current_revision
        self.notify_senders()
        # Fencing + durability gate: acked == applied at majority. On
        # timeout the local apply may still replicate later — the same
        # ambiguity etcd surfaces on a commit timeout — so the error
        # says so instead of pretending the write vanished.
        if not self._wait_commit(rev):
            # NOT released to watchers: the suffix stays behind the
            # commit gate — it either commits later (a sender ack
            # releases it) or dies with this reign (snapshot rejoin
            # discards it), so no watcher ever saw the ambiguity
            return {"ok": False, "error":
                    "EdlStoreError: replication commit timeout — write "
                    "not acknowledged at majority (may still commit)"}
        self.store.release_fanout(rev)
        return resp


# --------------------------------------------------------------------------
# one process-worth of replica: server + node


class ReplicaServer:
    """One store replica: a ``StoreServer`` (TCP, watch streams, lease
    sweeper) with a ``ReplicaNode`` plugged into its request path."""

    def __init__(self, endpoint: str, port: int, *, host: str = "127.0.0.1",
                 group_endpoints: list[str],
                 group: str = "shard0",
                 topology: dict[str, list[str]] | None = None,
                 election_ttl: float | None = None,
                 sweep_interval: float = 0.25,
                 **node_kw):
        from edl_tpu.coord.server import StoreServer
        self.endpoint = endpoint
        self.node = ReplicaNode(endpoint, group_endpoints, group=group,
                                topology=topology,
                                election_ttl=election_ttl, **node_kw)
        self.server = StoreServer(port=port, host=host,
                                  store=self.node.store,
                                  sweep_interval=sweep_interval,
                                  node=self.node)
        self.port = self.server.port

    def start(self) -> "ReplicaServer":
        self.server.start()
        self.node.start()
        return self

    def stop(self) -> None:
        self.node.stop(graceful=True)
        self.server.stop()

    def kill(self) -> None:
        """Crash: no resign, no graceful anything — peers pay the full
        lease-expiry price to take over (what the chaos tests measure)."""
        self.node.kill()
        self.server.stop()

    def __enter__(self) -> "ReplicaServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class ReplicaGroup:
    """In-process N-replica group (tests, bench, the CI dryrun)."""

    def __init__(self, n: int = 3, *, host: str = "127.0.0.1",
                 election_ttl: float = 0.6,
                 topology: dict[str, list[str]] | None = None,
                 group: str = "shard0", **node_kw):
        from edl_tpu.utils.net import free_port
        ports = [free_port() for _ in range(n)]
        self.endpoints = [f"{host}:{p}" for p in ports]
        self.servers = [
            ReplicaServer(self.endpoints[i], ports[i], host=host,
                          group_endpoints=self.endpoints, group=group,
                          topology=topology, election_ttl=election_ttl,
                          **node_kw)
            for i in range(n)
        ]

    @property
    def endpoints_spec(self) -> str:
        return ",".join(ep for ep, srv in zip(self.endpoints, self.servers)
                        if srv is not None)

    def start(self) -> "ReplicaGroup":
        for srv in self.servers:
            if srv is not None:
                srv.start()
        return self

    def leader(self) -> ReplicaServer | None:
        for srv in self.servers:
            if srv is not None and srv.node.is_leader():
                return srv
        return None

    def wait_leader(self, timeout: float = 15.0) -> ReplicaServer:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            srv = self.leader()
            if srv is not None:
                return srv
            time.sleep(0.02)
        raise EdlStoreError("no leader elected within "
                            f"{timeout}s among {self.endpoints}")

    def kill_leader(self) -> str:
        """Crash the current leader; returns its endpoint. The server
        slot becomes None — the group runs degraded, like production."""
        srv = self.wait_leader()
        srv.kill()
        self.servers[self.servers.index(srv)] = None
        return srv.endpoint

    def client(self, **kw) -> StoreClient:
        return StoreClient(self.endpoints_spec, **kw)

    def stop(self) -> None:
        for srv in self.servers:
            if srv is not None:
                srv.stop()

    def __enter__(self) -> "ReplicaGroup":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# --------------------------------------------------------------------------
# sharded client


class ShardedStoreClient(Store):
    """Store over a sharded topology: routes every op to the owning
    group's ``StoreClient`` (which handles leader failover within the
    group) instead of discovering ownership via REDIRECT bounces.

    Leases are **materialized lazily**: ``lease_grant`` returns a
    client-local virtual id; the first keyed op that uses it grants the
    real lease in that key's owner group and pins the virtual lease
    there (a Registration's grant-then-claim flow lands the lease
    exactly where its key lives). Using one lease across two groups is
    an error by construction — shard placement (``shard_key``) keeps a
    service's subtree in one group precisely so this never happens in
    the registry stack.

    Cross-shard reads: ``get_prefix``/``delete_prefix`` on a prefix
    shorter than the placement key fan out to every group and merge;
    ``watch``/``events_since`` raise instead (revisions are per-group —
    there is no global resume anchor), and ``try_watch`` turns that
    into the documented poll fallback.
    """

    def __init__(self, topology: dict[str, list[str]] | str, *,
                 timeout: float = 5.0, **client_kw):
        groups = parse_topology(topology) if isinstance(topology, str) \
            else topology
        self.router = ShardRouter(groups)
        self._clients = {g: StoreClient(",".join(eps), timeout=timeout,
                                        **client_kw)
                         for g, eps in groups.items()}
        self._vlock = threading.Lock()
        self._vleases: dict[int, dict] = {}  # guarded-by: _vlock
        self._next_v = 1                     # guarded-by: _vlock

    # -- lease virtualization ----------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        with self._vlock:
            vid = self._next_v
            self._next_v += 1
            self._vleases[vid] = {"ttl": ttl, "group": None, "real": 0}
            return vid

    def _materialize(self, vid: int, group: str) -> int:
        if not vid:
            return 0
        with self._vlock:
            ent = self._vleases.get(vid)
            if ent is None:
                raise EdlStoreError(f"unknown virtual lease {vid}")
            if ent["group"] is None:
                ent["real"] = self._clients[group].lease_grant(ent["ttl"])
                ent["group"] = group
            elif ent["group"] != group:
                raise EdlStoreError(
                    f"lease {vid} pinned to shard group {ent['group']!r} "
                    f"cannot guard a key in {group!r} — one lease, one "
                    "shard (scope registrations to one service prefix)")
            return ent["real"]

    def lease_keepalive(self, lease: int) -> bool:
        with self._vlock:
            ent = self._vleases.get(lease)
        if ent is None:
            return False
        if ent["group"] is None:
            return True  # nothing granted server-side yet: cannot expire
        return self._clients[ent["group"]].lease_keepalive(ent["real"])

    def lease_revoke(self, lease: int) -> bool:
        with self._vlock:
            ent = self._vleases.pop(lease, None)
        if ent is None:
            return False
        if ent["group"] is None:
            return True
        return self._clients[ent["group"]].lease_revoke(ent["real"])

    # -- keyed ops ----------------------------------------------------------

    def _for_key(self, key: str) -> tuple[str, StoreClient]:
        group = self.router.owner(key)
        return group, self._clients[group]

    def put(self, key: str, value: str, lease: int = 0) -> int:
        group, client = self._for_key(key)
        return client.put(key, value, self._materialize(lease, group))

    def get(self, key: str) -> Record | None:
        return self._for_key(key)[1].get(key)

    def delete(self, key: str) -> bool:
        return self._for_key(key)[1].delete(key)

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        group, client = self._for_key(key)
        return client.put_if_absent(key, value,
                                    self._materialize(lease, group))

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        group, client = self._for_key(key)
        return client.compare_and_swap(key, expect, value,
                                       self._materialize(lease, group))

    # -- prefix ops ---------------------------------------------------------

    def _prefix_clients(self, prefix: str) -> list[StoreClient]:
        owner = self.router.owner_of_prefix(prefix)
        if owner == ShardRouter.SPANS:
            return list(self._clients.values())
        return [self._clients[owner]]

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        records: list[Record] = []
        rev = 0
        for client in self._prefix_clients(prefix):
            recs, r = client.get_prefix(prefix)
            records.extend(recs)
            rev = max(rev, r)  # cross-shard: NOT a resume anchor
        records.sort(key=lambda r: r.key)
        return records, rev

    def delete_prefix(self, prefix: str) -> int:
        return sum(c.delete_prefix(prefix)
                   for c in self._prefix_clients(prefix))

    def events_since(self, revision: int, prefix: str = ""
                     ) -> tuple[list[Event], int, bool]:
        owner = self.router.owner_of_prefix(prefix)
        if owner == ShardRouter.SPANS:
            raise EdlStoreError(
                "events_since needs a shard-scoped prefix in a sharded "
                "topology (revisions are per-group)")
        return self._clients[owner].events_since(revision, prefix)

    def watch(self, prefix: str = "", start_revision: int | None = None,
              heartbeat: float = 2.0) -> Watch:
        owner = self.router.owner_of_prefix(prefix)
        if owner == ShardRouter.SPANS:
            raise EdlStoreError(
                "watch needs a shard-scoped prefix in a sharded topology "
                "(try_watch falls back to polling)")
        return self._clients[owner].watch(prefix, start_revision,
                                          heartbeat=heartbeat)

    def ping(self) -> bool:
        return all(c.ping() for c in self._clients.values())

    def close(self) -> None:
        for client in self._clients.values():
            client.close()


# --------------------------------------------------------------------------
# CLI: logic selftest (stdlib-only) + leader-kill chaos dryrun


def selftest(verbose: bool = True) -> int:
    """Logic-level invariants, no sockets: shard routing stability,
    raw-apply idempotence, passive/active lease handoff, snapshot
    resync, log merge ordering, backoff bounds. Pure stdlib —
    asserted: the coordination plane must run on a scheduler node with
    no accelerator stack installed."""
    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        if verbose:
            print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    # shard_key pins a service subtree to one placement unit
    check(shard_key("/edl/teachers/nodes/h:1") == "/edl/teachers",
          "shard_key: service subtree collapses to /root/service")
    check(shard_key("/edl/teachers") == "/edl/teachers",
          "shard_key: the prefix itself maps identically")

    groups = parse_topology("a:1,b:1;c:1,d:1;e:1,f:1")
    check(list(groups) == ["shard0", "shard1", "shard2"],
          f"parse_topology: ;-groups auto-named (got {list(groups)})")
    named = parse_topology("users=a:1;jobs=b:1")
    check(set(named) == {"users", "jobs"},
          "parse_topology: explicit group names")
    chunked = parse_topology("a:1,b:1,c:1,d:1", shards=2)
    check([len(v) for v in chunked.values()] == [2, 2],
          "parse_topology: flat list chunked by shard count")

    router = ShardRouter(groups)
    svc_keys = [f"/edl/svc{i}/nodes/h:{j}" for i in range(40)
                for j in range(3)]
    stable = all(router.owner(k) == router.owner(shard_key(k))
                 for k in svc_keys)
    check(stable, "router: every key of a service lands with its prefix")
    spread = {router.owner(f"/edl/svc{i}/x") for i in range(40)}
    check(len(spread) == len(groups),
          f"router: 40 services spread over all {len(groups)} groups "
          f"(hit {len(spread)})")
    check(router.owner_of_prefix("/edl/") == ShardRouter.SPANS,
          "router: one-segment prefix spans shards")

    # raw-apply: a follower mirrors the leader's stream verbatim
    leader, follower = InMemStore(), InMemStore()
    follower.set_passive(True)
    lease = leader.lease_grant(30.0)
    leader.put("/j/a", "1")
    leader.put("/j/b", "2", lease=lease)
    leader.delete("/j/a")
    evs, rev, compacted = leader.events_since(0)
    check(not compacted, "leader history covers a fresh follower")
    for ev in evs:
        if ev.type == "PUT":
            rec = leader.get(ev.key)
            follower.apply_put(ev.key, ev.value, ev.revision,
                               rec.lease if rec
                               and rec.revision == ev.revision else 0)
        else:
            follower.apply_delete(ev.key, ev.value, ev.revision)
    check(follower.current_revision == rev,
          "follower revision tracks the leader's")
    check(follower.get("/j/a") is None and
          follower.get("/j/b").value == "2",
          "follower data mirrors the leader's")
    # replay the same events: idempotent, no new revisions
    for ev in evs:
        if ev.type == "PUT":
            follower.apply_put(ev.key, ev.value, ev.revision, 0)
    check(follower.current_revision == rev,
          "replayed entries dedupe (raw-apply is idempotent)")
    # promotion: lease->keys rebuilt from records, expiry works again
    follower.apply_lease(lease, 0.05)
    clock = [100.0]
    follower._clock = lambda: clock[0]
    follower.set_passive(False)
    clock[0] += 10.0  # well past the re-based now+ttl deadline
    follower.sweep()
    check(follower.get("/j/b") is None,
          "promoted follower resumes lease-expiry duty")

    # snapshot install: wholesale replace + watcher resync signal
    src, dst = InMemStore(), InMemStore()
    for i in range(5):
        src.put(f"/s/{i}", str(i))
    watch = dst.watch("")
    dst.install_snapshot(src.snapshot_state())
    batch = watch.get(timeout=1.0)
    check(batch is not None and batch.compacted,
          "snapshot install pushes an explicit compacted batch")
    check(dst.get("/s/3").value == "3"
          and dst.current_revision == src.current_revision,
          "snapshot carries records + revision")
    evs2, _, compacted2 = dst.events_since(0)
    check(compacted2 and not evs2,
          "pre-snapshot history reads as compacted on the follower")
    watch.cancel()

    # backoff: jittered within [base, max], grows, resets
    b = Backoff(base=0.1, max_delay=0.4, rng=random.Random(7))
    delays = [b.delay() for _ in range(6)]
    check(all(0.1 <= d <= 0.4 for d in delays),
          f"backoff delays bounded (got {[round(d, 3) for d in delays]})")
    b.reset()
    check(b.delay() <= 0.2, "backoff reset returns to the base window")

    heavy = [m for m in ("jax", "numpy") if m in sys.modules]
    check(not heavy,
          f"coordination plane imports stay jax/numpy-free (saw {heavy})")

    if failures:
        print(f"replication selftest: {len(failures)} failure(s)")
        return 1
    print("replication selftest: all checks passed")
    return 0


def dryrun(verbose: bool = True) -> int:
    """Leader-kill chaos, end to end over real sockets: a 3-replica
    group takes a registry-shaped write stream (the traffic a training
    resize generates) while a watcher consumes the event stream; the
    leader is crashed mid-stream (no resign — followers pay the full
    lease-expiry price); exits 1 unless every majority-acked write
    survives, the watch resumes by revision with ZERO lost and ZERO
    duplicated events, and a fresh leader emerges in bounded time."""
    acked: dict[str, int] = {}
    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        if verbose:
            print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    with ReplicaGroup(3, election_ttl=0.6) as group:
        first = group.wait_leader(timeout=20.0)
        check(first is not None, "initial election converges")
        client = group.client(timeout=3.0)
        watcher = group.client(timeout=3.0)
        watch = watcher.watch("/job/", start_revision=0)

        stop_writes = threading.Event()
        write_errors: list[str] = []

        def writer() -> None:
            # the resize-shaped stream: rank claims + util publishes
            i = 0
            while not stop_writes.is_set() and i < 400:
                key = f"/job/rank/{i % 16}"
                try:
                    rev = client.put(key, f"pod-{i}")
                    acked[f"pod-{i}"] = rev
                except EdlStoreError as exc:
                    write_errors.append(str(exc))
                i += 1
                time.sleep(0.005)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.6)  # let writes flow through the first leader
        killed = group.kill_leader()
        t0 = time.monotonic()
        second = group.wait_leader(timeout=20.0)
        failover_s = time.monotonic() - t0
        check(second.endpoint != killed,
              f"a different replica took over ({second.endpoint})")
        check(failover_s < 10.0,
              f"failover bounded (took {failover_s * 1e3:.0f} ms)")
        time.sleep(1.0)  # stream continues through the new leader
        stop_writes.set()
        t.join(timeout=10.0)

        # drain the watch: every acked revision exactly once, in order
        seen: dict[int, str] = {}
        duplicates = 0
        compacted = False
        deadline = time.monotonic() + 10.0
        max_acked = max(acked.values(), default=0)
        while time.monotonic() < deadline:
            batch = watch.get(timeout=0.5)
            if batch is None:
                if seen and max(seen) >= max_acked:
                    break
                continue
            compacted = compacted or batch.compacted
            for ev in batch.events:
                if ev.revision in seen:
                    duplicates += 1
                seen[ev.revision] = ev.value
        check(duplicates == 0,
              f"zero duplicate deliveries (got {duplicates})")
        check(not compacted,
              "no compaction: followers' history covered the resume point")
        lost = [v for v, rev in acked.items() if rev not in seen]
        check(not lost,
              f"zero acked events lost across the kill ({len(acked)} acked,"
              f" {len(lost)} missing)")
        check(all(seen[rev] == v for v, rev in acked.items()
                  if rev in seen),
              "delivered values match the acked writes")
        if verbose:
            print(f"     acked={len(acked)} delivered={len(seen)} "
                  f"failover={failover_s * 1e3:.0f}ms "
                  f"writer_errors={len(write_errors)}")
        watch.cancel()
        watcher.close()
        client.close()

    if failures:
        print(f"replication dryrun: {len(failures)} failure(s)")
        return 1
    print("replication dryrun: leader killed, zero events lost")
    return 0


def serve(args) -> int:
    """Run ONE replica as a standalone process (the production shape:
    one `serve` per pod of the store StatefulSet).

        python -m edl_tpu.coord.replication serve \\
            --endpoint h0:2379 --endpoints h0:2379,h1:2379,h2:2379
    """
    groups = parse_topology(args.endpoints)
    group = next((g for g, eps in groups.items() if args.endpoint in eps),
                 None)
    if group is None:
        raise SystemExit(f"--endpoint {args.endpoint} not present in "
                         f"--endpoints {args.endpoints}")
    _, port = split_endpoint(args.endpoint)
    server = ReplicaServer(
        args.endpoint, port, host=args.host,
        group_endpoints=groups[group], group=group,
        topology=groups if len(groups) > 1 else None,
        election_ttl=args.election_ttl or None)
    server.start()
    log.info("replica %s serving (group %s of %d, peers %s)",
             args.endpoint, group, len(groups),
             ",".join(server.node.peers) or "<none>")
    threading.Event().wait()  # serve forever
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="store replication subsystem: serve / chaos checks")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("selftest",
                   help="logic-level invariants (stdlib-only, no sockets)")
    sub.add_parser("dryrun",
                   help="3-replica leader-kill chaos over real sockets")
    srv = sub.add_parser("serve", help="run one replica process")
    srv.add_argument("--endpoint", required=True,
                     help="this replica's advertised host:port")
    srv.add_argument("--endpoints", required=True,
                     help="full topology (EDL_TPU_STORE_ENDPOINTS syntax)")
    srv.add_argument("--host", default="0.0.0.0", help="bind address")
    srv.add_argument("--election_ttl", type=float, default=0.0,
                     help="quorum-lease TTL override (0 = env/default)")
    args = parser.parse_args(argv)
    if args.cmd == "selftest":
        return selftest()
    if args.cmd == "serve":
        return serve(args)
    return dryrun()


if __name__ == "__main__":
    raise SystemExit(main())
