"""Redis-backed Store: the reference's second discovery flavor (C10-C14).

The reference duplicated its whole distill discovery stack over redis
(`python/paddle_edl/distill/redis/` — registry on TTL'd hashes
`/service/{name}/nodes/{server}`, redis_store.py:38-53, plus its own
balance server and registrar). Here the stack is already generic over
the `Store` interface, so the flavor is ONE class: `RedisStore` speaks
RESP2 (coord/resp.py) to a real redis — or the bundled `MiniRedis` —
and `ServiceRegistry`/`TeacherRegistrar`/`DiscoveryServer`/
`DistillReader` run over it unchanged. Select it anywhere a store
endpoint is accepted with a `redis://host:port` URI (`connect_store`).

Mapping:
- records live at their key as JSON ``{"v": value, "r": revision}``;
  revisions come from ``INCR !edl:rev`` so `get_prefix` stays
  monotonic (redis has no native revisions);
- a lease is ``!edl:lease:{id}`` (PEXPIRE'd) + a member set
  ``!edl:lease:{id}:k``; a key bound to the lease is written with
  ``SET ... PX ttl`` in ONE command (no TTL-less window a crash could
  leave behind), keepalive re-arms everything, revoke deletes — the
  TTL-key semantics the reference's registrar heartbeat relies on.
  The lease is validated BEFORE the key is written: a put against an
  expired lease must not resurrect the key (a dead teacher would stay
  routable forever);
- prefix reads use SCAN (cursor loop), not KEYS — the discovery server
  polls every tick and KEYS blocks a production redis on the whole
  keyspace;
- watches ride pub/sub: every mutation issued THROUGH this class also
  PUBLISHes a JSON event on ``!edl:events``, and ``watch(prefix)``
  subscribes on a dedicated connection. Pub/sub is fire-and-forget —
  no revision history, no replay — so the contract is weaker than the
  edl store's: a (re)connect and any requested ``start_revision``
  surface as an explicit ``compacted`` batch (consumer resyncs via
  ``get_prefix``), and TTL expiry emits NO event (redis expires keys
  silently) — which is exactly why every event consumer keeps its
  poll-resync safety net.
- scope matches the reference's: the redis flavor serves the
  DISCOVERY/DISTILL pillar. `compare_and_swap` is GET-compare-SET —
  correct only for single-writer keys (a Registration reclaiming its
  own key), which is all the discovery stack needs; CONTENDED cas
  (DistributedLock, task master, rank claims) and `events_since`
  history reads stay on the edl store, exactly as the reference kept
  its master on etcd. Out-of-scope methods raise EdlRedisError — a
  subclass of EdlStoreError, so the registry's bounded-retry paths
  treat it as a store failure rather than dying.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from collections import deque

from edl_tpu.coord.resp import (RespClient, RespError, encode_command,
                                read_reply)
from edl_tpu.coord.store import Event, Record, Store, Watch, WatchBatch
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.coord.redis_store")


class EdlRedisError(EdlStoreError):
    pass


_REV = "!edl:rev"
_LEASE_ID = "!edl:lease:id"
_EVENTS_CHANNEL = "!edl:events"


def _lease_key(lease: int) -> str:
    return f"!edl:lease:{lease}"


def _glob_escape(s: str) -> str:
    out = []
    for ch in s:
        if ch in "*?[]\\":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


class RedisStore(Store):
    """Store subset over RESP (see module docstring for the mapping)."""

    def __init__(self, endpoint: str, timeout: float = 10.0):
        self._endpoint = endpoint
        self._timeout = timeout
        self._client = RespClient(endpoint, timeout=timeout)

    def _publish_event(self, type_: str, key: str, value: str,
                       revision: int) -> None:
        """Best-effort watch feed: a failed PUBLISH only delays watchers
        until their resync tick — it must never fail the mutation."""
        if key.startswith("!edl:"):
            return  # bookkeeping keys are not record data
        try:
            self._client.command(
                "PUBLISH", _EVENTS_CHANNEL,
                json.dumps({"type": type_, "key": key, "value": value,
                            "revision": revision}, sort_keys=True))
        except EdlStoreError as exc:
            log.debug("event publish failed for %s %s: %s", type_, key, exc)

    def close(self) -> None:
        self._client.close()

    def ping(self) -> bool:
        try:
            return self._client.command("PING") == "PONG"
        except Exception:  # noqa: BLE001 — liveness probe
            return False

    # -- kv ----------------------------------------------------------------

    def _bump(self) -> int:
        return int(self._client.command("INCR", _REV))

    def _lease_ttl_ms(self, lease: int) -> int:
        """The live lease's REMAINING ttl (PTTL), so a key written late
        in a lease window expires WITH the lease rather than up to one
        full TTL after it — a dead teacher must not linger routable.
        Raises if the lease expired (validated BEFORE any key write —
        see module docstring)."""
        remaining = int(self._client.command("PTTL", _lease_key(lease)))
        if remaining < 0:  # -2 no key, -1 no TTL (never set by us)
            from edl_tpu.utils.exceptions import EdlLeaseExpired
            raise EdlLeaseExpired(f"lease {lease} unknown or expired")
        return max(1, remaining)

    def _detach(self, key: str, old_blob: str | None,
                new_lease: int) -> None:
        """SREM the key from a previous lease's member set when the
        binding changes — otherwise a stale lease's keepalive keeps
        re-arming (and its revoke deletes) a key it no longer owns
        (InMemStore._detach's semantics)."""
        rec = self._decode(key, old_blob)
        if rec is not None and rec.lease and rec.lease != new_lease:
            self._client.command("SREM", _lease_key(rec.lease) + ":k", key)

    def _set(self, key: str, value: str, lease: int,
             nx: bool) -> tuple[bool, int]:
        rev = self._bump()
        blob = json.dumps({"v": value, "r": rev, "l": lease})
        args = ["SET", key, blob]
        ttl_ms = 0
        if lease:
            ttl_ms = self._lease_ttl_ms(lease)  # validate first
            args += ["PX", str(ttl_ms)]  # atomic value+TTL
        if nx:
            args.append("NX")
        old = None if nx else self._client.command("GET", key)
        ok = self._client.command(*args)
        if ok is None:
            return False, rev
        self._detach(key, old, lease)
        if lease:
            members = _lease_key(lease) + ":k"
            self._client.command("SADD", members, key)
            self._client.command("PEXPIRE", members, ttl_ms)
        self._publish_event("PUT", key, value, rev)
        return True, rev

    def put(self, key: str, value: str, lease: int = 0) -> int:
        return self._set(key, value, lease, nx=False)[1]

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        return self._set(key, value, lease, nx=True)[0]

    def _decode(self, key: str, blob: str | None) -> Record | None:
        if blob is None:
            return None
        try:
            doc = json.loads(blob)
            # non-record values (the !edl: revision/lease bookkeeping
            # keys parse as bare ints) surface in whole-keyspace scans,
            # e.g. the Collector's store-health snapshot
            return Record(key=key, value=doc["v"], revision=int(doc["r"]),
                          lease=int(doc.get("l", 0)))
        except (json.JSONDecodeError, KeyError, ValueError, TypeError):
            return None

    def get(self, key: str) -> Record | None:
        return self._decode(key, self._client.command("GET", key))

    def _scan(self, pattern: str) -> list[str]:
        """Cursor-looped SCAN (KEYS blocks a production redis on the
        whole keyspace; the discovery server polls every tick)."""
        keys, cursor = [], "0"
        while True:
            reply = self._client.command("SCAN", cursor, "MATCH", pattern,
                                         "COUNT", "512")
            cursor, batch = reply[0], reply[1] or []
            keys.extend(batch)
            if cursor == "0":
                return keys

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        keys = self._scan(_glob_escape(prefix) + "*")
        # the !edl: bookkeeping namespace (revision/lease counters and
        # member sets) is not record data — InMemStore keeps its
        # equivalents out of the keyspace entirely, so whole-keyspace
        # scans (e.g. the Collector's store-health tick) must not
        # surface or MGET it here either
        if not prefix.startswith("!edl:"):
            keys = [k for k in keys if not k.startswith("!edl:")]
        recs = []
        if keys:
            blobs = self._client.command("MGET", *keys)
            for key, blob in zip(keys, blobs):
                rec = self._decode(key, blob)
                if rec is not None:
                    recs.append(rec)
        recs.sort(key=lambda r: r.key)
        rev = int(self._client.command("GET", _REV) or 0)
        return recs, rev

    def delete(self, key: str) -> bool:
        blob = self._client.command("GET", key)
        self._detach(key, blob, new_lease=0)
        deleted = int(self._client.command("DEL", key)) > 0
        if deleted:
            rec = self._decode(key, blob)
            self._publish_event("DELETE", key,
                                rec.value if rec is not None else "",
                                self._bump())
        return deleted

    def delete_prefix(self, prefix: str) -> int:
        keys = self._scan(_glob_escape(prefix) + "*")
        if not keys:
            return 0
        blobs = self._client.command("MGET", *keys)
        for key, blob in zip(keys, blobs):
            self._detach(key, blob, new_lease=0)
        count = int(self._client.command("DEL", *keys))
        for key, blob in zip(keys, blobs):
            rec = self._decode(key, blob)
            if rec is not None:
                self._publish_event("DELETE", key, rec.value, self._bump())
        return count

    # -- leases ------------------------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        lease = int(self._client.command("INCR", _LEASE_ID))
        ttl_ms = max(1, int(ttl * 1000))
        self._client.command("SET", _lease_key(lease),
                             json.dumps({"ttl_ms": ttl_ms}),
                             "PX", str(ttl_ms))
        return lease

    def lease_keepalive(self, lease: int) -> bool:
        blob = self._client.command("GET", _lease_key(lease))
        if blob is None:
            return False  # expired: the registrar re-registers
        ttl_ms = int(json.loads(blob)["ttl_ms"])
        self._client.command("PEXPIRE", _lease_key(lease), ttl_ms)
        members = self._client.command(
            "SMEMBERS", _lease_key(lease) + ":k") or []
        self._client.command("PEXPIRE", _lease_key(lease) + ":k", ttl_ms)
        for key in members:
            self._client.command("PEXPIRE", key, ttl_ms)
        return True

    def lease_revoke(self, lease: int) -> bool:
        members = list(self._client.command(
            "SMEMBERS", _lease_key(lease) + ":k") or [])
        existed = self._client.command("GET", _lease_key(lease)) is not None
        blobs = self._client.command("MGET", *members) if members else []
        targets = members + [_lease_key(lease), _lease_key(lease) + ":k"]
        self._client.command("DEL", *targets)
        # explicit revoke emits DELETE events (InMemStore parity); TTL
        # EXPIRY still cannot — redis drops keys silently, which is why
        # watch consumers keep their resync net (module docstring)
        for key, blob in zip(members, blobs):
            rec = self._decode(key, blob)
            if rec is not None:
                self._publish_event("DELETE", key, rec.value, self._bump())
        return existed

    # -- cas: SINGLE-WRITER keys only ---------------------------------------

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        """GET-compare-SET, NOT atomic across writers.

        Sufficient for the discovery pillar's use — `Registration`
        reclaiming ITS OWN key after a lease lapse (registry.py:89),
        where this registrant is the only writer of the key. CONTENDED
        cas users (DistributedLock, task master, rank claims) must stay
        on the edl store: two racing writers can both pass the compare
        here. The reference drew the same line — its redis flavor
        served discovery only, the master stayed on etcd.
        """
        cur = self._client.command("GET", key)
        cur_value = None if cur is None else \
            (self._decode(key, cur).value
             if self._decode(key, cur) is not None else None)
        if cur_value != expect:
            return False
        if expect is None:
            return self.put_if_absent(key, value, lease)
        return self._set(key, value, lease, nx=False)[0]

    # -- watches (pub/sub) ---------------------------------------------------

    def watch(self, prefix: str = "", start_revision: int | None = None
              ) -> "RedisWatch":
        """Pub/sub watch (module docstring has the weakened contract:
        no replay, so resume requests and reconnects surface as
        ``compacted`` batches, and TTL expiry emits no event)."""
        return RedisWatch(self._endpoint, prefix,
                          start_revision=start_revision,
                          timeout=self._timeout)

    # -- out of the redis flavor's scope ------------------------------------

    def events_since(self, revision: int, prefix: str = ""):
        raise EdlRedisError(
            "event history reads are not served by the redis flavor; "
            "use watch() (pub/sub, no replay) or poll get_prefix")


class RedisWatch(Watch):
    """SUBSCRIBE-fed watch stream over a dedicated RESP connection.

    Messages are ``{"type", "key", "value", "revision"}`` JSON on the
    ``!edl:events`` channel, filtered by prefix client-side. Because
    pub/sub has no history, anything that may have dropped messages —
    an explicit ``start_revision`` (we cannot replay) and every
    (re)connect after the first — delivers a ``compacted`` batch so the
    consumer resyncs via ``get_prefix``.
    """

    expiry_events = False  # TTL expiry is silent in redis

    def __init__(self, endpoint: str, prefix: str, *,
                 start_revision: int | None = None, timeout: float = 10.0,
                 reconnect_backoff: float = 0.2):
        from edl_tpu.utils.net import split_endpoint
        self._addr = split_endpoint(endpoint)
        self.prefix = prefix
        self._timeout = timeout
        self._backoff = reconnect_backoff
        self._cond = threading.Condition()
        self._queue: deque[WatchBatch] = deque()
        self._stop = threading.Event()
        self._sock: socket.socket | None = None
        self._last_rev = 0
        if start_revision is not None:
            # no replay over pub/sub: force an immediate resync
            self._queue.append(WatchBatch((), start_revision, True))
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"redis-watch-{prefix}")
        self._thread.start()

    def _run(self) -> None:
        first = True
        while not self._stop.is_set():
            rf = None
            try:
                sock = socket.create_connection(self._addr,
                                                timeout=self._timeout)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(None)  # idle channels are legal
            except OSError:
                if self._stop.wait(max(self._backoff, 1.0)):
                    return
                continue
            with self._cond:
                if self._stop.is_set():
                    sock.close()
                    return
                self._sock = sock
            try:
                sock.sendall(encode_command(("SUBSCRIBE", _EVENTS_CHANNEL)))
                rf = sock.makefile("rb")
                read_reply(rf)  # ["subscribe", channel, 1]
                if not first:
                    # the gap had no feed: events may be lost
                    self._push(WatchBatch((), self._last_rev, True))
                first = False
                while True:
                    msg = read_reply(rf)
                    if not (isinstance(msg, list) and len(msg) == 3
                            and msg[0] == "message"):
                        continue
                    try:
                        doc = json.loads(msg[2])
                        ev = Event(doc["type"], doc["key"], doc["value"],
                                   int(doc["revision"]))
                    except (json.JSONDecodeError, KeyError, TypeError,
                            ValueError):
                        continue
                    self._last_rev = max(self._last_rev, ev.revision)
                    if ev.key.startswith(self.prefix):
                        self._push(WatchBatch((ev,), ev.revision))
            except (RespError, OSError):
                pass
            finally:
                with self._cond:
                    self._sock = None
                if rf is not None:
                    try:
                        rf.close()
                    except OSError:
                        pass
                try:
                    sock.close()
                except OSError:
                    pass
            self._stop.wait(self._backoff)

    def _push(self, batch: WatchBatch) -> None:
        with self._cond:
            self._queue.append(batch)
            self._cond.notify_all()

    def get(self, timeout: float | None = None) -> WatchBatch | None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._stop.is_set():
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._queue:
                return self._queue.popleft()
            return None

    def progress_revision(self) -> int | None:
        with self._cond:
            if self._queue:
                return None
            return self._last_rev

    def cancel(self) -> None:
        self._stop.set()
        with self._cond:
            sock = self._sock
            self._sock = None
            self._cond.notify_all()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)

    @property
    def cancelled(self) -> bool:
        return self._stop.is_set()


def connect_store(endpoint: str, timeout: float = 10.0) -> Store:
    """Store from an endpoint string — every consumer's connection path.

    - ``redis://host:port`` -> RedisStore (discovery flavor);
    - ``h0:p,h1:p,h2:p`` -> StoreClient over the replica list
      (transparent leader failover within the group);
    - ``g0=h0:p,h1:p;g1=h2:p,...`` (or a flat list with
      ``EDL_TPU_STORE_SHARDS`` > 1) -> ShardedStoreClient routing
      registry prefixes across replica groups.
    """
    if endpoint.startswith("redis://"):
        return RedisStore(endpoint[len("redis://"):], timeout=timeout)
    from edl_tpu.utils import config
    if ";" in endpoint or config.env_int("EDL_TPU_STORE_SHARDS", 1) > 1:
        from edl_tpu.coord.replication import ShardedStoreClient
        return ShardedStoreClient(endpoint, timeout=timeout)
    from edl_tpu.coord.client import StoreClient
    return StoreClient(endpoint, timeout=timeout)
