"""Redis-backed Store: the reference's second discovery flavor (C10-C14).

The reference duplicated its whole distill discovery stack over redis
(`python/paddle_edl/distill/redis/` — registry on TTL'd hashes
`/service/{name}/nodes/{server}`, redis_store.py:38-53, plus its own
balance server and registrar). Here the stack is already generic over
the `Store` interface, so the flavor is ONE class: `RedisStore` speaks
RESP2 (coord/resp.py) to a real redis — or the bundled `MiniRedis` —
and `ServiceRegistry`/`TeacherRegistrar`/`DiscoveryServer`/
`DistillReader` run over it unchanged. Select it anywhere a store
endpoint is accepted with a `redis://host:port` URI (`connect_store`).

Mapping:
- records live at their key as JSON ``{"v": value, "r": revision}``;
  revisions come from ``INCR !edl:rev`` so `get_prefix` stays
  monotonic (redis has no native revisions);
- a lease is ``!edl:lease:{id}`` (PEXPIRE'd) + a member set
  ``!edl:lease:{id}:k``; a key bound to the lease is written with
  ``SET ... PX ttl`` in ONE command (no TTL-less window a crash could
  leave behind), keepalive re-arms everything, revoke deletes — the
  TTL-key semantics the reference's registrar heartbeat relies on.
  The lease is validated BEFORE the key is written: a put against an
  expired lease must not resurrect the key (a dead teacher would stay
  routable forever);
- prefix reads use SCAN (cursor loop), not KEYS — the discovery server
  polls every tick and KEYS blocks a production redis on the whole
  keyspace;
- scope matches the reference's: the redis flavor serves the
  DISCOVERY/DISTILL pillar. `compare_and_swap` is GET-compare-SET —
  correct only for single-writer keys (a Registration reclaiming its
  own key), which is all the discovery stack needs; CONTENDED cas
  (DistributedLock, task master, rank claims) and event watches stay
  on the edl store, exactly as the reference kept its master on etcd.
  Out-of-scope methods raise EdlRedisError — a subclass of
  EdlStoreError, so the registry's bounded-retry paths treat it as a
  store failure rather than dying.
"""

from __future__ import annotations

import json

from edl_tpu.coord.resp import RespClient
from edl_tpu.coord.store import Record, Store
from edl_tpu.utils.exceptions import EdlStoreError


class EdlRedisError(EdlStoreError):
    pass


_REV = "!edl:rev"
_LEASE_ID = "!edl:lease:id"


def _lease_key(lease: int) -> str:
    return f"!edl:lease:{lease}"


def _glob_escape(s: str) -> str:
    out = []
    for ch in s:
        if ch in "*?[]\\":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


class RedisStore(Store):
    """Store subset over RESP (see module docstring for the mapping)."""

    def __init__(self, endpoint: str, timeout: float = 10.0):
        self._client = RespClient(endpoint, timeout=timeout)

    def close(self) -> None:
        self._client.close()

    def ping(self) -> bool:
        try:
            return self._client.command("PING") == "PONG"
        except Exception:  # noqa: BLE001 — liveness probe
            return False

    # -- kv ----------------------------------------------------------------

    def _bump(self) -> int:
        return int(self._client.command("INCR", _REV))

    def _lease_ttl_ms(self, lease: int) -> int:
        """The live lease's REMAINING ttl (PTTL), so a key written late
        in a lease window expires WITH the lease rather than up to one
        full TTL after it — a dead teacher must not linger routable.
        Raises if the lease expired (validated BEFORE any key write —
        see module docstring)."""
        remaining = int(self._client.command("PTTL", _lease_key(lease)))
        if remaining < 0:  # -2 no key, -1 no TTL (never set by us)
            from edl_tpu.utils.exceptions import EdlLeaseExpired
            raise EdlLeaseExpired(f"lease {lease} unknown or expired")
        return max(1, remaining)

    def _detach(self, key: str, old_blob: str | None,
                new_lease: int) -> None:
        """SREM the key from a previous lease's member set when the
        binding changes — otherwise a stale lease's keepalive keeps
        re-arming (and its revoke deletes) a key it no longer owns
        (InMemStore._detach's semantics)."""
        rec = self._decode(key, old_blob)
        if rec is not None and rec.lease and rec.lease != new_lease:
            self._client.command("SREM", _lease_key(rec.lease) + ":k", key)

    def _set(self, key: str, value: str, lease: int,
             nx: bool) -> tuple[bool, int]:
        rev = self._bump()
        blob = json.dumps({"v": value, "r": rev, "l": lease})
        args = ["SET", key, blob]
        ttl_ms = 0
        if lease:
            ttl_ms = self._lease_ttl_ms(lease)  # validate first
            args += ["PX", str(ttl_ms)]  # atomic value+TTL
        if nx:
            args.append("NX")
        old = None if nx else self._client.command("GET", key)
        ok = self._client.command(*args)
        if ok is None:
            return False, rev
        self._detach(key, old, lease)
        if lease:
            members = _lease_key(lease) + ":k"
            self._client.command("SADD", members, key)
            self._client.command("PEXPIRE", members, ttl_ms)
        return True, rev

    def put(self, key: str, value: str, lease: int = 0) -> int:
        return self._set(key, value, lease, nx=False)[1]

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        return self._set(key, value, lease, nx=True)[0]

    def _decode(self, key: str, blob: str | None) -> Record | None:
        if blob is None:
            return None
        try:
            doc = json.loads(blob)
            # non-record values (the !edl: revision/lease bookkeeping
            # keys parse as bare ints) surface in whole-keyspace scans,
            # e.g. the Collector's store-health snapshot
            return Record(key=key, value=doc["v"], revision=int(doc["r"]),
                          lease=int(doc.get("l", 0)))
        except (json.JSONDecodeError, KeyError, ValueError, TypeError):
            return None

    def get(self, key: str) -> Record | None:
        return self._decode(key, self._client.command("GET", key))

    def _scan(self, pattern: str) -> list[str]:
        """Cursor-looped SCAN (KEYS blocks a production redis on the
        whole keyspace; the discovery server polls every tick)."""
        keys, cursor = [], "0"
        while True:
            reply = self._client.command("SCAN", cursor, "MATCH", pattern,
                                         "COUNT", "512")
            cursor, batch = reply[0], reply[1] or []
            keys.extend(batch)
            if cursor == "0":
                return keys

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        keys = self._scan(_glob_escape(prefix) + "*")
        # the !edl: bookkeeping namespace (revision/lease counters and
        # member sets) is not record data — InMemStore keeps its
        # equivalents out of the keyspace entirely, so whole-keyspace
        # scans (e.g. the Collector's store-health tick) must not
        # surface or MGET it here either
        if not prefix.startswith("!edl:"):
            keys = [k for k in keys if not k.startswith("!edl:")]
        recs = []
        if keys:
            blobs = self._client.command("MGET", *keys)
            for key, blob in zip(keys, blobs):
                rec = self._decode(key, blob)
                if rec is not None:
                    recs.append(rec)
        recs.sort(key=lambda r: r.key)
        rev = int(self._client.command("GET", _REV) or 0)
        return recs, rev

    def delete(self, key: str) -> bool:
        self._detach(key, self._client.command("GET", key), new_lease=0)
        return int(self._client.command("DEL", key)) > 0

    def delete_prefix(self, prefix: str) -> int:
        keys = self._scan(_glob_escape(prefix) + "*")
        if not keys:
            return 0
        for key, blob in zip(keys, self._client.command("MGET", *keys)):
            self._detach(key, blob, new_lease=0)
        return int(self._client.command("DEL", *keys))

    # -- leases ------------------------------------------------------------

    def lease_grant(self, ttl: float) -> int:
        lease = int(self._client.command("INCR", _LEASE_ID))
        ttl_ms = max(1, int(ttl * 1000))
        self._client.command("SET", _lease_key(lease),
                             json.dumps({"ttl_ms": ttl_ms}),
                             "PX", str(ttl_ms))
        return lease

    def lease_keepalive(self, lease: int) -> bool:
        blob = self._client.command("GET", _lease_key(lease))
        if blob is None:
            return False  # expired: the registrar re-registers
        ttl_ms = int(json.loads(blob)["ttl_ms"])
        self._client.command("PEXPIRE", _lease_key(lease), ttl_ms)
        members = self._client.command(
            "SMEMBERS", _lease_key(lease) + ":k") or []
        self._client.command("PEXPIRE", _lease_key(lease) + ":k", ttl_ms)
        for key in members:
            self._client.command("PEXPIRE", key, ttl_ms)
        return True

    def lease_revoke(self, lease: int) -> bool:
        members = self._client.command(
            "SMEMBERS", _lease_key(lease) + ":k") or []
        existed = self._client.command("GET", _lease_key(lease)) is not None
        targets = list(members) + [_lease_key(lease),
                                   _lease_key(lease) + ":k"]
        self._client.command("DEL", *targets)
        return existed

    # -- cas: SINGLE-WRITER keys only ---------------------------------------

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        """GET-compare-SET, NOT atomic across writers.

        Sufficient for the discovery pillar's use — `Registration`
        reclaiming ITS OWN key after a lease lapse (registry.py:89),
        where this registrant is the only writer of the key. CONTENDED
        cas users (DistributedLock, task master, rank claims) must stay
        on the edl store: two racing writers can both pass the compare
        here. The reference drew the same line — its redis flavor
        served discovery only, the master stayed on etcd.
        """
        cur = self._client.command("GET", key)
        cur_value = None if cur is None else \
            (self._decode(key, cur).value
             if self._decode(key, cur) is not None else None)
        if cur_value != expect:
            return False
        if expect is None:
            return self.put_if_absent(key, value, lease)
        return self._set(key, value, lease, nx=False)[0]

    # -- out of the redis flavor's scope ------------------------------------

    def events_since(self, revision: int, prefix: str = ""):
        raise EdlRedisError(
            "event watches are not served by the redis flavor; watchers "
            "over redis poll get_prefix (ServiceWatcher already does)")


def connect_store(endpoint: str, timeout: float = 10.0) -> Store:
    """Store from an endpoint string: `redis://host:port` -> RedisStore,
    bare `host:port` -> the edl store client (the default)."""
    if endpoint.startswith("redis://"):
        return RedisStore(endpoint[len("redis://"):], timeout=timeout)
    from edl_tpu.coord.client import StoreClient
    return StoreClient(endpoint, timeout=timeout)
