"""Coordination store: keys + leases + revisions + event history.

This is the framework's membership/state substrate — the capability of the
reference's etcd v3 usage (discovery/etcd_client.py:52-253: TTL leases,
watches, put-if-absent rank claims; pkg/master/etcd_client.go:49-176:
locks/leader state). Rather than depending on an external etcd binary, the
store is part of the framework: ``InMemStore`` is the engine, served over TCP
by ``StoreServer`` (Python) or the C++ ``edl-store`` daemon (native/), and
used in-process by unit tests.

Semantics:

- Global monotonically increasing **revision**; every mutation gets one.
- **Leases**: ``lease_grant(ttl)`` returns an id; keys put with a lease are
  deleted (with DELETE events) when the lease expires; ``lease_keepalive``
  refreshes the deadline. Expiry is checked lazily on every public call and
  by the server's sweeper thread.
- **Events**: bounded history of PUT/DELETE, queryable by
  ``events_since(revision, prefix)``; if the window was compacted the caller
  gets ``compacted=True`` and must fall back to a full ``get_prefix``.
- **Watches**: ``watch(prefix, start_revision)`` subscribes to the same
  PUT/DELETE stream as a push feed (the reference's etcd v3 watch,
  discovery/etcd_client.py:115-149) — per-watcher bounded queue, lease-expiry
  DELETEs included, compaction/overflow signalled as a ``compacted`` batch so
  the consumer resyncs via ``get_prefix``. ``EDL_TPU_COORD_WATCH=0`` disables
  watches everywhere (``try_watch`` returns None) and every consumer falls
  back to its original polling loop.
- **CAS**: ``put_if_absent`` is the rank-claim primitive
  (reference utils/register.py:60-88).
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field

from edl_tpu.utils import config


@dataclass(frozen=True)
class Record:
    key: str
    value: str
    revision: int
    lease: int = 0


@dataclass(frozen=True)
class Event:
    type: str  # "PUT" | "DELETE"
    key: str
    value: str
    revision: int


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: set[str] = field(default_factory=set)


@dataclass(frozen=True)
class WatchBatch:
    """One watch delivery: events (revision-ordered), the resume anchor
    `revision` (resume a new watch from here to miss nothing), and the
    `compacted` flag — True means events were lost (history compaction
    or watcher-queue overflow) and the consumer MUST resync with a full
    ``get_prefix`` before trusting incremental state again."""
    events: tuple[Event, ...]
    revision: int
    compacted: bool = False


def watch_enabled() -> bool:
    """The EDL_TPU_COORD_WATCH=0 escape hatch: restores pure polling in
    every converted consumer (read per call so tests can flip it)."""
    return config.env_flag("EDL_TPU_COORD_WATCH", True)


def watch_resync_interval(default: float = 30.0) -> float:
    """How often event-driven consumers still run their full-poll resync
    safety net (EDL_TPU_WATCH_RESYNC_S). The net catches what events
    cannot promise: missed wakeups, redis TTL expiry (no event), and
    user-callback failures."""
    return max(0.1, config.env_float("EDL_TPU_WATCH_RESYNC_S", default))


def try_watch(store: "Store", prefix: str = "", start_revision: int | None
              = None) -> "Watch | None":
    """A watch on `store`, or None when watches are disabled
    (EDL_TPU_COORD_WATCH=0), unsupported by this store flavor, or the
    subscribe itself fails — callers treat None as 'keep polling'."""
    if not watch_enabled():
        return None
    try:
        return store.watch(prefix, start_revision=start_revision)
    except Exception:  # noqa: BLE001 — unsupported flavor / transient
        return None


class Watch:
    """Handle for one watch stream (InMemStore, StoreClient and
    RedisStore each implement this shape).

    - ``get(timeout)`` -> next WatchBatch, or None on timeout/cancel.
    - ``progress_revision()`` -> the resume anchor when the queue is
      drained (None while batches are pending), used for heartbeats.
    - ``cancel()`` unsubscribes and wakes any blocked ``get``.
    """

    prefix: str = ""
    created_revision: int = 0
    # False when the flavor cannot deliver lease/TTL-expiry DELETEs
    # (redis pub/sub): consumers then keep their original poll cadence
    # for the resync net instead of the slow watch-mode cadence.
    expiry_events: bool = True

    def get(self, timeout: float | None = None) -> WatchBatch | None:
        raise NotImplementedError

    def progress_revision(self) -> int | None:
        raise NotImplementedError

    def cancel(self) -> None:
        raise NotImplementedError

    @property
    def cancelled(self) -> bool:
        raise NotImplementedError

    def __enter__(self) -> "Watch":
        return self

    def __exit__(self, *exc) -> None:
        self.cancel()


class InMemWatch(Watch):
    """In-process watch: bounded per-watcher queue fed under the store
    lock by ``_emit``. Overflow collapses the queue into one compacted
    batch rather than blocking the store or growing without bound."""

    def __init__(self, store: "InMemStore", prefix: str, max_pending: int):
        self._store = store
        self.prefix = prefix
        self._max = max_pending
        # Resume fence: events at or below this revision are already in
        # the subscriber's hands (its start_revision) and must never be
        # re-delivered — including by a commit-gate release of entries
        # that were applied-but-unreleased when the watcher resumed.
        self.min_revision = 0
        self._cond = threading.Condition()
        self._queue: deque[WatchBatch] = deque()  # guarded-by: _cond
        self._pending_events = 0                  # guarded-by: _cond
        self._cancelled = False                   # guarded-by: _cond

    # -- producer side (store lock held) ------------------------------------

    def _push(self, ev: Event) -> None:
        self._push_events((ev,))

    def _push_events(self, evs: tuple[Event, ...]) -> None:
        """Range-batched delivery: one WatchBatch — one revision header
        on the wire — carrying every event of a multi-key mutation
        (lease-expiry sweep, delete_prefix, a commit-gate release)
        instead of one batch per event."""
        with self._cond:
            if self._cancelled or not evs:
                return
            if self._pending_events + len(evs) > self._max:
                # lagging consumer: drop everything, force a resync
                self._queue.clear()
                self._pending_events = 0
                self._queue.append(WatchBatch((), evs[-1].revision, True))
            else:
                self._pending_events += len(evs)
                self._queue.append(WatchBatch(tuple(evs), evs[-1].revision))
            self._cond.notify_all()

    def _push_compacted(self, revision: int) -> None:
        with self._cond:
            if self._cancelled:
                return
            self._queue.append(WatchBatch((), revision, True))
            self._cond.notify_all()

    # -- consumer side -------------------------------------------------------

    def get(self, timeout: float | None = None) -> WatchBatch | None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._cancelled:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._queue:
                batch = self._queue.popleft()
                self._pending_events -= len(batch.events)
                return batch
            return None

    def progress_revision(self) -> int | None:
        # Atomic with _emit (both take the store lock): a None answer
        # means a batch is pending; a revision answer means every event
        # <= that revision in this prefix has already been delivered —
        # safe to advertise as the client's resume anchor.
        with self._store._lock:
            with self._cond:
                if self._queue or self._cancelled:
                    return None
                return self._store._visible_revision_locked()

    def cancel(self) -> None:
        self._store._unwatch(self)
        with self._cond:
            self._cancelled = True
            self._cond.notify_all()

    @property
    def cancelled(self) -> bool:
        with self._cond:
            return self._cancelled


class Store:
    """Abstract store API (implemented by InMemStore and StoreClient)."""

    def put(self, key: str, value: str, lease: int = 0) -> int:
        raise NotImplementedError

    def get(self, key: str) -> Record | None:
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> int:
        raise NotImplementedError

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        raise NotImplementedError

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        raise NotImplementedError

    def lease_grant(self, ttl: float) -> int:
        raise NotImplementedError

    def lease_keepalive(self, lease: int) -> bool:
        raise NotImplementedError

    def lease_revoke(self, lease: int) -> bool:
        raise NotImplementedError

    def events_since(self, revision: int, prefix: str = ""
                     ) -> tuple[list[Event], int, bool]:
        """Return (events, current_revision, compacted)."""
        raise NotImplementedError

    def watch(self, prefix: str = "", start_revision: int | None = None
              ) -> "Watch":
        """Subscribe to PUT/DELETE events under `prefix` as a push
        stream. ``start_revision`` replays history after that revision
        first (compacted batch when the window no longer covers it);
        None starts from now. Flavors without watches raise — use
        ``try_watch`` to fall back to polling."""
        raise NotImplementedError


_MAX_EVENTS = 4096
_MAX_WATCH_PENDING = 4096
# ttl given to a lease resurrected from replicated records alone (its
# grant entry predates the follower's catch-up window)
_DEFAULT_LEASE_TTL = 10.0


class InMemStore(Store):
    """Single-process store engine. Thread-safe; time injectable for tests."""

    def __init__(self, clock=time.monotonic, max_events: int = _MAX_EVENTS):
        self._clock = clock
        self._lock = threading.RLock()
        self._data: dict[str, Record] = {}    # guarded-by: _lock
        self._leases: dict[int, _Lease] = {}  # guarded-by: _lock
        self._revision = 0                    # guarded-by: _lock
        self._next_lease = 1                  # guarded-by: _lock
        self._events: list[Event] = []        # guarded-by: _lock
        self._max_events = max_events
        # events older than this were compacted
        self._first_event_rev = 1             # guarded-by: _lock
        self._watchers: list[InMemWatch] = []  # guarded-by: _lock
        # public Store-API calls served (bench: poll- vs watch-mode
        # request volume); watch deliveries are pushes, not requests
        self.op_count = 0                     # guarded-by: _lock
        # Passive mode (replication followers): lease expiry is the
        # LEADER's decision, shipped here as replicated DELETE events —
        # a follower that also expired locally would double-delete with
        # revisions the leader never assigned.
        self._passive = False                 # guarded-by: _lock
        # watch fan-out accounting: event pushes delivered to watcher
        # queues (the obs registry's view of the push plane)
        self._fanout_events = 0               # guarded-by: _lock
        self._expired_leases = 0              # guarded-by: _lock
        # Commit-gated watch fan-out (replicated stores only): when
        # gated, _emit buffers events instead of pushing them, and
        # release_fanout(commit_rev) delivers everything at or below
        # the majority-committed revision. Watchers therefore never
        # observe a doomed leader's uncommitted suffix — entries a
        # failover discards and whose revision numbers the next reign
        # reuses (the r18 branch anomaly, now closed). Ungated stores
        # (the default) are unchanged: fan-out at apply time.
        self._gated = False                   # guarded-by: _lock
        self._gate_rev = 0                    # guarded-by: _lock
        self._pending_fanout: deque[Event] = deque()  # guarded-by: _lock
        # log-compaction + delta-snapshot accounting
        self._events_compacted = 0            # guarded-by: _lock
        self._delta_snapshots = 0             # guarded-by: _lock

    # -- internals ---------------------------------------------------------

    def _bump(self) -> int:  # holds-lock: _lock
        self._revision += 1
        return self._revision

    def _emit(self, ev: Event) -> None:  # holds-lock: _lock
        self._emit_many([ev])

    def _emit_many(self, evs: list[Event]) -> None:  # holds-lock: _lock
        """Append + fan out a multi-event mutation as ONE WatchBatch per
        watcher (range-batched event frames) instead of one per event —
        a host-lease expiry sweeping 40 pod registrations costs each
        watcher one queue append, not 40."""
        if not evs:
            return
        self._events.extend(evs)
        if len(self._events) > self._max_events:
            drop = len(self._events) - self._max_events
            self._first_event_rev = self._events[drop].revision
            del self._events[:drop]
        if self._gated:
            ready = [ev for ev in evs if ev.revision <= self._gate_rev]
            self._pending_fanout.extend(
                ev for ev in evs if ev.revision > self._gate_rev)
            if ready:
                self._fanout_push_many(ready)
            return
        self._fanout_push_many(evs)

    def _fanout_push(self, ev: Event) -> None:  # holds-lock: _lock
        self._fanout_push_many([ev])

    def _fanout_push_many(self, evs: list[Event]) -> None:  # holds-lock: _lock
        for watcher in self._watchers:
            fit = [ev for ev in evs
                   if ev.key.startswith(watcher.prefix)
                   and ev.revision > watcher.min_revision]
            if fit:
                watcher._push_events(tuple(fit))
                self._fanout_events += len(fit)

    def _expire(self) -> None:  # holds-lock: _lock
        if self._passive:
            return
        now = self._clock()
        dead = [l for l in self._leases.values() if l.deadline <= now]
        self._expired_leases += len(dead)
        for lease in dead:
            # one event batch per expired lease: every key the lease
            # carried (a whole host's pod registrations under lease
            # coalescing) sweeps in a single delivery
            evs = []
            for key in sorted(lease.keys):
                rec = self._data.pop(key, None)
                if rec is not None:
                    evs.append(Event("DELETE", key, rec.value, self._bump()))
            del self._leases[lease.id]
            self._emit_many(evs)

    def _check_lease(self, lease: int) -> None:  # holds-lock: _lock
        if lease and lease not in self._leases:
            from edl_tpu.utils.exceptions import EdlLeaseExpired
            raise EdlLeaseExpired(f"lease {lease} unknown or expired")

    def _detach(self, key: str, rec: Record) -> None:  # holds-lock: _lock
        if rec.lease and rec.lease in self._leases:
            self._leases[rec.lease].keys.discard(key)

    # -- Store API ---------------------------------------------------------

    def put(self, key: str, value: str, lease: int = 0) -> int:
        with self._lock:
            self.op_count += 1
            self._expire()
            self._check_lease(lease)
            old = self._data.get(key)
            if old is not None:
                self._detach(key, old)
            rev = self._bump()
            self._data[key] = Record(key, value, rev, lease)
            if lease:
                self._leases[lease].keys.add(key)
            self._emit(Event("PUT", key, value, rev))
            return rev

    def get(self, key: str) -> Record | None:
        with self._lock:
            self.op_count += 1
            self._expire()
            return self._data.get(key)

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        with self._lock:
            self.op_count += 1
            self._expire()
            recs = sorted(
                (r for k, r in self._data.items() if k.startswith(prefix)),
                key=lambda r: r.key,
            )
            return recs, self._revision

    def delete(self, key: str) -> bool:
        with self._lock:
            self.op_count += 1
            self._expire()
            rec = self._data.pop(key, None)
            if rec is None:
                return False
            self._detach(key, rec)
            self._emit(Event("DELETE", key, rec.value, self._bump()))
            return True

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            self.op_count += 1
            self._expire()
            keys = [k for k in self._data if k.startswith(prefix)]
            evs = []
            for k in keys:
                rec = self._data.pop(k)
                self._detach(k, rec)
                evs.append(Event("DELETE", k, rec.value, self._bump()))
            self._emit_many(evs)
            return len(keys)

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        with self._lock:
            self.op_count += 1
            self._expire()
            if key in self._data:
                return False
            self._check_lease(lease)
            rev = self._bump()
            self._data[key] = Record(key, value, rev, lease)
            if lease:
                self._leases[lease].keys.add(key)
            self._emit(Event("PUT", key, value, rev))
            return True

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        with self._lock:
            self.op_count += 1
            self._expire()
            cur = self._data.get(key)
            if expect is None:
                if cur is not None:
                    return False
            elif cur is None or cur.value != expect:
                return False
            self.put(key, value, lease)
            return True

    def lease_grant(self, ttl: float) -> int:
        with self._lock:
            self.op_count += 1
            self._expire()
            lease_id = self._next_lease
            self._next_lease += 1
            self._leases[lease_id] = _Lease(lease_id, ttl, self._clock() + ttl)
            return lease_id

    def lease_keepalive(self, lease: int) -> bool:
        with self._lock:
            self.op_count += 1
            self._expire()
            entry = self._leases.get(lease)
            if entry is None:
                return False
            entry.deadline = self._clock() + entry.ttl
            return True

    def lease_revoke(self, lease: int) -> bool:
        with self._lock:
            self.op_count += 1
            self._expire()
            entry = self._leases.pop(lease, None)
            if entry is None:
                return False
            evs = []
            for key in sorted(entry.keys):
                rec = self._data.pop(key, None)
                if rec is not None:
                    evs.append(Event("DELETE", key, rec.value, self._bump()))
            self._emit_many(evs)
            return True

    def events_since(self, revision: int, prefix: str = ""
                     ) -> tuple[list[Event], int, bool]:
        with self._lock:
            self.op_count += 1
            self._expire()
            if revision + 1 < self._first_event_rev:
                return [], self._revision, True
            evs = [e for e in self._events
                   if e.revision > revision and e.key.startswith(prefix)]
            return evs, self._revision, False

    @property
    def current_revision(self) -> int:
        with self._lock:
            return self._revision

    def sweep(self) -> None:
        """Expire due leases now (called by the server's sweeper thread).
        Not counted as a request: it is the server's own maintenance, and
        it is what turns lease expiry into DELETE events for watchers
        even when no client traffic arrives."""
        with self._lock:
            self._expire()

    # -- commit-gated fan-out (replicated stores) ----------------------------

    def set_fanout_gate(self, gated: bool) -> None:
        """Turn commit-gated watch delivery on/off. On enable, the gate
        starts at the current revision (everything already applied is
        considered committed — the replica plane enables the gate at
        construction, before any traffic). Disabling releases whatever
        is pending."""
        with self._lock:
            if self._gated == gated:
                return
            self._gated = gated
            self._gate_rev = self._revision
            if not gated:
                flush = list(self._pending_fanout)
                self._pending_fanout.clear()
                self._fanout_push_many(flush)

    @property
    def fanout_gated(self) -> bool:
        with self._lock:
            return self._gated

    def release_fanout(self, revision: int) -> None:
        """Deliver buffered events up to ``revision`` (the majority-
        committed revision, supplied by the replica plane). Idempotent;
        a revision ahead of the local log clamps to what exists."""
        with self._lock:
            if not self._gated:
                return
            revision = min(revision, self._revision)
            if revision <= self._gate_rev:
                return
            self._gate_rev = revision
            ready = []
            while self._pending_fanout \
                    and self._pending_fanout[0].revision <= revision:
                ready.append(self._pending_fanout.popleft())
            # one batch for the whole released range: a commit covering
            # N entries reaches each watcher as one frame, not N
            self._fanout_push_many(ready)

    def _visible_revision_locked(self) -> int:  # holds-lock: _lock
        """The revision watchers may use as a resume anchor: everything
        at or below it has been (or could have been) delivered. Gated
        stores answer the commit gate, not the raw apply point — an
        anchor past the gate could skip a reused revision after
        failover."""
        return self._gate_rev if self._gated else self._revision

    # -- watches -------------------------------------------------------------

    def watch(self, prefix: str = "", start_revision: int | None = None,
              max_pending: int = _MAX_WATCH_PENDING) -> InMemWatch:
        with self._lock:
            self._expire()
            watcher = InMemWatch(self, prefix, max_pending)
            watcher.created_revision = self._visible_revision_locked()
            if start_revision is not None:
                watcher.min_revision = start_revision
                if start_revision + 1 < self._first_event_rev:
                    watcher._push_compacted(self._visible_revision_locked())
                else:
                    # gated: replay only the committed prefix — the
                    # uncommitted tail is exactly _pending_fanout and
                    # will be pushed to this (now registered) watcher
                    # when the commit gate advances over it
                    horizon = self._gate_rev if self._gated \
                        else self._revision
                    replay = tuple(
                        ev for ev in self._events
                        if start_revision < ev.revision <= horizon
                        and ev.key.startswith(prefix))
                    watcher._push_events(replay)
            self._watchers.append(watcher)
            return watcher

    def _unwatch(self, watcher: InMemWatch) -> None:
        with self._lock:
            try:
                self._watchers.remove(watcher)
            except ValueError:
                pass  # already cancelled

    def watcher_count(self) -> int:
        with self._lock:
            return len(self._watchers)

    def stats(self) -> dict:
        """Engine counters as a dict view — what StoreServer registers
        into the per-process obs registry (doc/design_obs.md): request
        volume, watch fan-out, lease churn, history pressure."""
        with self._lock:
            return {"keys": len(self._data),
                    "revision": self._revision,
                    "ops": self.op_count,
                    "leases_live": len(self._leases),
                    "leases_expired": self._expired_leases,
                    "watchers": len(self._watchers),
                    "watch_fanout_events": self._fanout_events,
                    "events_buffered": len(self._events),
                    "events_compacted": self._events_compacted,
                    "delta_snapshots": self._delta_snapshots,
                    "fanout_gated": self._gated,
                    "fanout_pending": len(self._pending_fanout),
                    "passive": self._passive}

    def compact(self, revision: int, keep: int = 512) -> int:
        """Drop event history at or below ``revision``, always retaining
        the newest ``keep`` events as a resume cushion. Watchers resumed
        below the new floor get the normal ``compacted`` resync; the
        leader calls this once every peer's match revision has passed
        the compaction point, so healthy followers never pay it."""
        with self._lock:
            cut = 0
            limit = max(0, len(self._events) - max(0, keep))
            while cut < limit and self._events[cut].revision <= revision:
                cut += 1
            if cut:
                self._first_event_rev = self._events[cut].revision
                del self._events[:cut]
                self._events_compacted += cut
            return cut

    # -- replication raw-apply (coord/replication.py) ------------------------
    #
    # Followers mirror the leader's mutation log verbatim: the leader
    # assigned the revisions, so the apply path takes them as given
    # instead of minting new ones, never runs lease expiry (passive
    # mode), and still fans events out to local watchers — which is what
    # lets a follower serve reads and revision-resumable watch streams.

    def set_passive(self, passive: bool) -> None:
        """Follower mode on/off. Entering active (leader) mode rebuilds
        the lease->keys index from the records themselves (replicated
        PUTs carry the lease id) and restarts every lease's clock at
        now+ttl: the new leader cannot know how much TTL was left on the
        old leader's clock, so it gives every lease one full period —
        live owners keepalive long before that, dead owners expire one
        TTL late at worst (never early, which is the dangerous side)."""
        with self._lock:
            if self._passive == passive:
                return
            self._passive = passive
            if not passive:
                now = self._clock()
                for lease in self._leases.values():
                    lease.keys.clear()
                    lease.deadline = now + lease.ttl
                for key, rec in self._data.items():
                    if rec.lease:
                        entry = self._leases.get(rec.lease)
                        if entry is None:
                            # grant entry lost in catch-up (only its keys
                            # replicated): resurrect with a default ttl —
                            # the owner's keepalive re-arms it
                            entry = _Lease(rec.lease, _DEFAULT_LEASE_TTL,
                                           now + _DEFAULT_LEASE_TTL)
                            self._leases[rec.lease] = entry
                            self._next_lease = max(self._next_lease,
                                                   rec.lease + 1)
                        entry.keys.add(key)

    def apply_put(self, key: str, value: str, revision: int,
                  lease: int = 0) -> None:
        """Replicated PUT at the leader's revision (idempotent: a replay
        at or below the applied revision is a no-op)."""
        with self._lock:
            if revision <= self._revision:
                return
            old = self._data.get(key)
            if old is not None:
                self._detach(key, old)
            self._data[key] = Record(key, value, revision, lease)
            if lease:
                entry = self._leases.get(lease)
                if entry is None:
                    entry = _Lease(lease, _DEFAULT_LEASE_TTL,
                                   self._clock() + _DEFAULT_LEASE_TTL)
                    self._leases[lease] = entry
                self._next_lease = max(self._next_lease, lease + 1)
                entry.keys.add(key)
            self._revision = max(self._revision, revision)
            self._emit(Event("PUT", key, value, revision))

    def apply_delete(self, key: str, value: str, revision: int) -> None:
        """Replicated DELETE (lease expiry on the leader arrives here
        too — it is just a DELETE event in the log)."""
        with self._lock:
            if revision <= self._revision:
                return
            rec = self._data.pop(key, None)
            if rec is not None:
                self._detach(key, rec)
            self._revision = max(self._revision, revision)
            self._emit(Event("DELETE", key, value, revision))

    def apply_lease(self, lease_id: int, ttl: float) -> None:
        """Replicated lease grant/keepalive: (re)arm the follower-side
        deadline from ITS clock. Deadlines only matter after promotion
        (set_passive(False) re-bases them anyway); tracking them here
        keeps the table warm and the id counter monotonic."""
        with self._lock:
            entry = self._leases.get(lease_id)
            if entry is None:
                entry = _Lease(lease_id, ttl, 0.0)
                self._leases[lease_id] = entry
            entry.ttl = ttl
            entry.deadline = self._clock() + ttl
            self._next_lease = max(self._next_lease, lease_id + 1)

    def apply_lease_gone(self, lease_id: int) -> None:
        """Replicated revoke/expiry: the key DELETEs ride the event log
        separately; this only drops the table entry."""
        with self._lock:
            self._leases.pop(lease_id, None)

    def snapshot_state(self) -> dict:
        """Full-state document for follower catch-up when the event
        history no longer covers its revision (see install_snapshot)."""
        with self._lock:
            return {
                "revision": self._revision,
                "records": [[r.key, r.value, r.revision, r.lease]
                            for r in self._data.values()],
                "leases": [[l.id, l.ttl] for l in self._leases.values()],
            }

    def state_digest(self) -> dict:
        """Compact fingerprint of local state for delta-snapshot
        negotiation: per-key [key, revision, crc32(value)]. The value
        crc matters — a dirty ex-leader can hold the SAME revision
        number with a DIFFERENT value (uncommitted suffix, revisions
        reused by the next reign), so revision equality alone would
        silently keep divergent records."""
        with self._lock:
            return {
                "revision": self._revision,
                "keys": [[r.key, r.revision,
                          zlib.crc32(r.value.encode("utf-8"))]
                         for r in self._data.values()],
            }

    def snapshot_delta(self, digest: dict) -> dict:
        """Delta-compressed snapshot against a follower's digest: only
        records the follower lacks or holds divergently (``set``), plus
        keys it must drop (``del``). Leases ship in full — the table is
        tiny next to the keyspace. ``base`` records the digest size the
        delta was computed against (observability only)."""
        with self._lock:
            theirs = {row[0]: (int(row[1]), int(row[2]))
                      for row in digest.get("keys", ())}
            set_rows = []
            for key, rec in self._data.items():
                have = theirs.get(key)
                if have is None or have != (
                        rec.revision,
                        zlib.crc32(rec.value.encode("utf-8"))):
                    set_rows.append([rec.key, rec.value, rec.revision,
                                     rec.lease])
            del_keys = [k for k in theirs if k not in self._data]
            return {
                "revision": self._revision,
                "set": set_rows,
                "del": del_keys,
                "leases": [[l.id, l.ttl] for l in self._leases.values()],
                "base": len(theirs),
            }

    def install_snapshot_delta(self, doc: dict) -> None:
        """Apply a delta snapshot over current state. Same watcher
        contract as a full install: history before the snapshot
        revision is unknowable, so every local watcher gets a
        ``compacted`` resync batch."""
        with self._lock:
            for key in doc.get("del", ()):
                self._data.pop(key, None)
            for row in doc.get("set", ()):
                self._data[row[0]] = Record(row[0], row[1], row[2], row[3])
            self._leases = {}
            now = self._clock()
            for lease_id, ttl in doc.get("leases", ()):
                self._leases[lease_id] = _Lease(lease_id, ttl, now + ttl)
                self._next_lease = max(self._next_lease, lease_id + 1)
            # lease->keys index rebuilds on promotion (set_passive)
            self._revision = max(self._revision, int(doc.get("revision", 0)))
            self._events = []
            self._first_event_rev = self._revision + 1
            self._pending_fanout.clear()
            self._gate_rev = self._revision
            self._delta_snapshots += 1
            for watcher in self._watchers:
                watcher._push_compacted(self._revision)

    def install_snapshot(self, doc: dict) -> None:
        """Replace local state wholesale (lagging or divergent follower).
        Event history before the snapshot revision is gone by
        construction, so every local watcher gets an explicit
        ``compacted`` batch — the same resync contract as history
        compaction; a watch consumer cannot tell the difference and
        does not need to."""
        with self._lock:
            self._data = {r[0]: Record(r[0], r[1], r[2], r[3])
                          for r in doc.get("records", ())}
            self._leases = {}
            now = self._clock()
            for lease_id, ttl in doc.get("leases", ()):
                self._leases[lease_id] = _Lease(lease_id, ttl, now + ttl)
                self._next_lease = max(self._next_lease, lease_id + 1)
            self._revision = max(self._revision, int(doc.get("revision", 0)))
            self._events = []
            self._first_event_rev = self._revision + 1
            # a gated store's buffered-but-unreleased tail is exactly
            # the divergent suffix a snapshot rejoin discards: drop it
            # (watchers resync via the compacted batch below and never
            # see the doomed branch)
            self._pending_fanout.clear()
            self._gate_rev = self._revision
            for watcher in self._watchers:
                watcher._push_compacted(self._revision)
