"""Coordination store: keys + leases + revisions + event history.

This is the framework's membership/state substrate — the capability of the
reference's etcd v3 usage (discovery/etcd_client.py:52-253: TTL leases,
watches, put-if-absent rank claims; pkg/master/etcd_client.go:49-176:
locks/leader state). Rather than depending on an external etcd binary, the
store is part of the framework: ``InMemStore`` is the engine, served over TCP
by ``StoreServer`` (Python) or the C++ ``edl-store`` daemon (native/), and
used in-process by unit tests.

Semantics:

- Global monotonically increasing **revision**; every mutation gets one.
- **Leases**: ``lease_grant(ttl)`` returns an id; keys put with a lease are
  deleted (with DELETE events) when the lease expires; ``lease_keepalive``
  refreshes the deadline. Expiry is checked lazily on every public call and
  by the server's sweeper thread.
- **Events**: bounded history of PUT/DELETE, queryable by
  ``events_since(revision, prefix)``; if the window was compacted the caller
  gets ``compacted=True`` and must fall back to a full ``get_prefix``.
- **CAS**: ``put_if_absent`` is the rank-claim primitive
  (reference utils/register.py:60-88).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Record:
    key: str
    value: str
    revision: int
    lease: int = 0


@dataclass(frozen=True)
class Event:
    type: str  # "PUT" | "DELETE"
    key: str
    value: str
    revision: int


@dataclass
class _Lease:
    id: int
    ttl: float
    deadline: float
    keys: set[str] = field(default_factory=set)


class Store:
    """Abstract store API (implemented by InMemStore and StoreClient)."""

    def put(self, key: str, value: str, lease: int = 0) -> int:
        raise NotImplementedError

    def get(self, key: str) -> Record | None:
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> int:
        raise NotImplementedError

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        raise NotImplementedError

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        raise NotImplementedError

    def lease_grant(self, ttl: float) -> int:
        raise NotImplementedError

    def lease_keepalive(self, lease: int) -> bool:
        raise NotImplementedError

    def lease_revoke(self, lease: int) -> bool:
        raise NotImplementedError

    def events_since(self, revision: int, prefix: str = ""
                     ) -> tuple[list[Event], int, bool]:
        """Return (events, current_revision, compacted)."""
        raise NotImplementedError


_MAX_EVENTS = 4096


class InMemStore(Store):
    """Single-process store engine. Thread-safe; time injectable for tests."""

    def __init__(self, clock=time.monotonic, max_events: int = _MAX_EVENTS):
        self._clock = clock
        self._lock = threading.RLock()
        self._data: dict[str, Record] = {}
        self._leases: dict[int, _Lease] = {}
        self._revision = 0
        self._next_lease = 1
        self._events: list[Event] = []
        self._max_events = max_events
        self._first_event_rev = 1  # events older than this were compacted

    # -- internals ---------------------------------------------------------

    def _bump(self) -> int:
        self._revision += 1
        return self._revision

    def _emit(self, ev: Event) -> None:
        self._events.append(ev)
        if len(self._events) > self._max_events:
            drop = len(self._events) - self._max_events
            self._first_event_rev = self._events[drop].revision
            del self._events[:drop]

    def _expire(self) -> None:
        now = self._clock()
        dead = [l for l in self._leases.values() if l.deadline <= now]
        for lease in dead:
            for key in sorted(lease.keys):
                rec = self._data.pop(key, None)
                if rec is not None:
                    self._emit(Event("DELETE", key, rec.value, self._bump()))
            del self._leases[lease.id]

    def _check_lease(self, lease: int) -> None:
        if lease and lease not in self._leases:
            from edl_tpu.utils.exceptions import EdlLeaseExpired
            raise EdlLeaseExpired(f"lease {lease} unknown or expired")

    def _detach(self, key: str, rec: Record) -> None:
        if rec.lease and rec.lease in self._leases:
            self._leases[rec.lease].keys.discard(key)

    # -- Store API ---------------------------------------------------------

    def put(self, key: str, value: str, lease: int = 0) -> int:
        with self._lock:
            self._expire()
            self._check_lease(lease)
            old = self._data.get(key)
            if old is not None:
                self._detach(key, old)
            rev = self._bump()
            self._data[key] = Record(key, value, rev, lease)
            if lease:
                self._leases[lease].keys.add(key)
            self._emit(Event("PUT", key, value, rev))
            return rev

    def get(self, key: str) -> Record | None:
        with self._lock:
            self._expire()
            return self._data.get(key)

    def get_prefix(self, prefix: str) -> tuple[list[Record], int]:
        with self._lock:
            self._expire()
            recs = sorted(
                (r for k, r in self._data.items() if k.startswith(prefix)),
                key=lambda r: r.key,
            )
            return recs, self._revision

    def delete(self, key: str) -> bool:
        with self._lock:
            self._expire()
            rec = self._data.pop(key, None)
            if rec is None:
                return False
            self._detach(key, rec)
            self._emit(Event("DELETE", key, rec.value, self._bump()))
            return True

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            self._expire()
            keys = [k for k in self._data if k.startswith(prefix)]
            for k in keys:
                rec = self._data.pop(k)
                self._detach(k, rec)
                self._emit(Event("DELETE", k, rec.value, self._bump()))
            return len(keys)

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        with self._lock:
            self._expire()
            if key in self._data:
                return False
            self._check_lease(lease)
            rev = self._bump()
            self._data[key] = Record(key, value, rev, lease)
            if lease:
                self._leases[lease].keys.add(key)
            self._emit(Event("PUT", key, value, rev))
            return True

    def compare_and_swap(self, key: str, expect: str | None, value: str,
                         lease: int = 0) -> bool:
        with self._lock:
            self._expire()
            cur = self._data.get(key)
            if expect is None:
                if cur is not None:
                    return False
            elif cur is None or cur.value != expect:
                return False
            self.put(key, value, lease)
            return True

    def lease_grant(self, ttl: float) -> int:
        with self._lock:
            self._expire()
            lease_id = self._next_lease
            self._next_lease += 1
            self._leases[lease_id] = _Lease(lease_id, ttl, self._clock() + ttl)
            return lease_id

    def lease_keepalive(self, lease: int) -> bool:
        with self._lock:
            self._expire()
            entry = self._leases.get(lease)
            if entry is None:
                return False
            entry.deadline = self._clock() + entry.ttl
            return True

    def lease_revoke(self, lease: int) -> bool:
        with self._lock:
            self._expire()
            entry = self._leases.pop(lease, None)
            if entry is None:
                return False
            for key in sorted(entry.keys):
                rec = self._data.pop(key, None)
                if rec is not None:
                    self._emit(Event("DELETE", key, rec.value, self._bump()))
            return True

    def events_since(self, revision: int, prefix: str = ""
                     ) -> tuple[list[Event], int, bool]:
        with self._lock:
            self._expire()
            if revision + 1 < self._first_event_rev:
                return [], self._revision, True
            evs = [e for e in self._events
                   if e.revision > revision and e.key.startswith(prefix)]
            return evs, self._revision, False

    def sweep(self) -> None:
        """Expire due leases now (called by the server's sweeper thread)."""
        with self._lock:
            self._expire()
