"""Service registry on top of the coordination store.

Key convention (same spirit as the reference's
``/{root}/{service}/nodes/{server}`` with TTL leases,
discovery/etcd_client.py:181-196 and distill/redis/redis_store.py:38-45):

    /{root}/{service}/nodes/{server}  ->  JSON {"server": ..., "info": ...}

Pieces:

- ``ServiceRegistry.get_service[_with_revision]`` — snapshot reads
  (reference discovery/etcd_client.py:89-113).
- ``Registration`` — ephemeral registration: lease + keepalive thread +
  bounded re-register after expiry (reference discovery/register.py:41-77:
  refresh every ttl/6, re-register after expiry, bounded retries).
- ``ServiceWatcher`` — fires deduplicated add/remove/update callbacks
  from the store's watch stream (reference discovery/etcd_client.py:
  115-149 did this over etcd watches); the original poll loop is
  demoted to a slow resync safety net, and remains the primary path
  when watches are unavailable (redis TTL expiry emits no event) or
  disabled (EDL_TPU_COORD_WATCH=0).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass

from edl_tpu.coord.store import Store
from edl_tpu.coord.client import LeaseKeeper
from edl_tpu.utils import unique_name
from edl_tpu.utils.exceptions import EdlRegisterError, EdlStoreError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.coord.registry")


@dataclass(frozen=True)
class ServerMeta:
    server: str   # "host:port"
    info: str     # opaque utilization/meta string
    revision: int = 0


class Registration:
    """Live ephemeral registration of one server under one service.

    Each Registration instance carries a unique ``token`` stored in the key's
    value. Re-registration after lease loss only reclaims the key if it is
    absent or still carries *our* token — a replacement process that
    legitimately re-claimed the same server identity is never stolen from.
    """

    def __init__(self, registry: "ServiceRegistry", service: str, server: str,
                 info: str, ttl: float, max_reregister: int = 45):
        self._registry = registry
        self.service = service
        self.server = server
        self.info = info
        self.ttl = ttl
        self.token = unique_name.client_id()
        self._max_reregister = max_reregister
        self._keeper: LeaseKeeper | None = None
        self._stopped = threading.Event()
        # Serializes _register/_on_lost/stop so a concurrent stop() cannot
        # leave a freshly created keeper running.
        self._lock = threading.Lock()
        with self._lock:
            self._register(initial=True)

    @property
    def key(self) -> str:
        return self._registry.node_key(self.service, self.server)

    def _value(self) -> str:
        return json.dumps({"server": self.server, "info": self.info,
                           "token": self.token})

    def _register(self, initial: bool) -> None:
        """Claim the key. Caller holds self._lock."""
        store = self._registry.store
        lease = store.lease_grant(self.ttl)
        if not store.put_if_absent(self.key, self._value(), lease):
            cur = store.get(self.key)
            owned = False
            if cur is not None:
                try:
                    owned = json.loads(cur.value).get("token") == self.token
                except json.JSONDecodeError:
                    pass
            if not (owned and not initial
                    and store.compare_and_swap(self.key, cur.value,
                                               self._value(), lease)):
                store.lease_revoke(lease)
                raise EdlRegisterError(
                    f"{self.key} already registered by another server")
        keeper = LeaseKeeper(
            store, lease, interval=max(self.ttl / 6.0, 0.05),
            on_lost=self._on_lost)
        if self._stopped.is_set():
            # stop() ran while we were registering — undo immediately.
            store.lease_revoke(lease)
            return
        self._keeper = keeper
        keeper.start()

    def _on_lost(self) -> None:
        for attempt in range(self._max_reregister):
            if self._stopped.is_set():
                return
            try:
                with self._lock:
                    if self._stopped.is_set():
                        return
                    self._register(initial=False)
                log.info("re-registered %s after lease loss (attempt %d)",
                         self.key, attempt + 1)
                return
            except (EdlStoreError, EdlRegisterError) as exc:
                log.warning("re-register %s failed: %s", self.key, exc)
                self._stopped.wait(0.5)
        log.error("giving up re-registering %s", self.key)

    def update_info(self, info: str) -> None:
        with self._lock:
            self.info = info
            if self._keeper is not None:
                self._registry.store.put(self.key, self._value(),
                                         self._keeper.lease)

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            if self._keeper is not None:
                self._keeper.stop(revoke=True)
                self._keeper = None


class ServiceWatcher:
    """Membership watcher: event-driven callbacks + poll-resync net.

    When the store serves watches, add/remove/update callbacks fire at
    event latency (PUT/DELETE on the service prefix, including
    lease-expiry DELETEs) and the full ``get_prefix`` diff only runs
    every ``resync_interval`` as a safety net (or immediately after a
    compacted batch or a throwing callback). Without watches
    (EDL_TPU_COORD_WATCH=0, redis flavor outage) the original
    ``interval`` poll loop is the whole mechanism.
    """

    def __init__(self, registry: "ServiceRegistry", service: str,
                 on_add=None, on_remove=None, on_update=None,
                 interval: float = 1.0, resync_interval: float | None = None):
        self._registry = registry
        self._service = service
        self._on_add = on_add
        self._on_remove = on_remove
        self._on_update = on_update
        self._interval = interval
        self._resync_interval = resync_interval
        self._stop = threading.Event()
        self._known: dict[str, ServerMeta] = {}
        self._watch = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"watch-{service}")

    def start(self) -> "ServiceWatcher":
        # Subscribe BEFORE the initial sync: events that land while the
        # snapshot is read are buffered and deduplicated afterwards
        # (same info + revision -> no second callback), so there is no
        # blind window between snapshot and stream.
        from edl_tpu.coord.store import try_watch
        self._watch = try_watch(
            self._registry.store,
            self._registry.service_prefix(self._service))
        # Initial sync is best-effort: a transient store error here must not
        # leave the caller holding a watcher whose thread never started —
        # the loop will converge on the next event/tick.
        self._safe_sync()
        self._thread.start()
        return self

    def _sync(self) -> None:
        metas = self._registry.get_service(self._service)
        now = {m.server: m for m in metas}
        # _known is updated only AFTER a callback succeeds: if a consumer
        # callback throws (e.g. while splicing a hash ring), the event is
        # re-delivered on the next poll instead of being lost forever.
        for server in list(self._known):
            if server not in now:
                meta = self._known[server]
                if self._on_remove:
                    self._on_remove(meta)
                self._known.pop(server, None)
        for server, meta in now.items():
            old = self._known.get(server)
            if old is None:
                if self._on_add:
                    self._on_add(meta)
                self._known[server] = meta
            elif old.info != meta.info or old.revision != meta.revision:
                if self._on_update:
                    self._on_update(meta)
                self._known[server] = meta

    def _safe_sync(self) -> None:
        try:
            self._sync()
        except Exception as exc:
            # Never let a poll error or a throwing user callback kill the
            # watch thread — a silently-dead watcher means a permanently
            # stale membership view.
            log.warning("watch %s poll failed: %s: %s", self._service,
                        type(exc).__name__, exc)

    def _apply_events(self, events) -> None:
        """Incremental `_sync`: one event, one callback. `_known` is
        only updated after the callback returns, so a throwing consumer
        gets the event redelivered by the resync diff (same contract as
        the poll path)."""
        prefix = self._registry.service_prefix(self._service)
        for ev in events:
            server = ev.key[len(prefix):]
            try:
                if ev.type == "DELETE":
                    meta = self._known.get(server)
                    if meta is None:
                        continue
                    if self._on_remove:
                        self._on_remove(meta)
                    self._known.pop(server, None)
                    continue
                try:
                    doc = json.loads(ev.value)
                    meta = ServerMeta(doc["server"], doc.get("info", ""),
                                      ev.revision)
                except (json.JSONDecodeError, KeyError, TypeError):
                    # same skip rule as get_service: a malformed value
                    # must not fabricate membership the resync diff
                    # would then "remove"
                    log.warning("malformed registry value at %s", ev.key)
                    continue
                old = self._known.get(server)
                if old is None:
                    if self._on_add:
                        self._on_add(meta)
                    self._known[server] = meta
                elif old.info != meta.info or old.revision != meta.revision:
                    if self._on_update:
                        self._on_update(meta)
                    self._known[server] = meta
            except Exception as exc:  # noqa: BLE001 — user callback threw
                log.warning("watch %s callback failed on %s %s: %s",
                            self._service, ev.type, ev.key, exc)
                self._safe_sync()  # redeliver via the snapshot diff

    def _run(self) -> None:
        if self._watch is None:
            while not self._stop.wait(self._interval):
                self._safe_sync()
            return
        from edl_tpu.coord.store import watch_resync_interval
        if self._resync_interval is not None:
            resync = self._resync_interval
        elif not self._watch.expiry_events:
            # redis pub/sub can't push TTL-expiry DELETEs: dead-server
            # removal still rides the poll, so keep the poll cadence
            resync = self._interval
        else:
            resync = watch_resync_interval(
                default=max(self._interval * 10, 30.0))
        next_resync = time.monotonic() + resync
        while not self._stop.is_set():
            batch = self._watch.get(
                timeout=max(0.0, next_resync - time.monotonic()))
            if self._stop.is_set():
                return
            if batch is None:  # resync safety net tick
                self._safe_sync()
                next_resync = time.monotonic() + resync
            elif batch.compacted:
                self._safe_sync()
            else:
                self._apply_events(batch.events)

    def servers(self) -> list[ServerMeta]:
        return sorted(self._known.values(), key=lambda m: m.server)

    def stop(self) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.cancel()
        self._thread.join(timeout=2.0)


class ServiceRegistry:
    def __init__(self, store: Store, root: str = "edl"):
        self.store = store
        self.root = root.strip("/")

    def service_prefix(self, service: str) -> str:
        return f"/{self.root}/{service}/nodes/"

    def node_key(self, service: str, server: str) -> str:
        return self.service_prefix(service) + server

    # -- reads -------------------------------------------------------------

    def get_service(self, service: str) -> list[ServerMeta]:
        return self.get_service_with_revision(service)[0]

    def get_service_with_revision(self, service: str
                                  ) -> tuple[list[ServerMeta], int]:
        recs, rev = self.store.get_prefix(self.service_prefix(service))
        metas = []
        for rec in recs:
            try:
                doc = json.loads(rec.value)
                metas.append(ServerMeta(doc["server"], doc.get("info", ""),
                                        rec.revision))
            except (json.JSONDecodeError, KeyError):
                log.warning("malformed registry value at %s", rec.key)
        return metas, rev

    # -- writes ------------------------------------------------------------

    def register(self, service: str, server: str, info: str = "",
                 ttl: float = 10.0) -> Registration:
        return Registration(self, service, server, info, ttl)

    def register_permanent(self, service: str, server: str, info: str = "") -> None:
        value = json.dumps({"server": server, "info": info})
        self.store.put(self.node_key(service, server), value)

    def deregister(self, service: str, server: str) -> bool:
        return self.store.delete(self.node_key(service, server))

    # -- watch -------------------------------------------------------------

    def watch_service(self, service: str, on_add=None, on_remove=None,
                      on_update=None, interval: float = 1.0,
                      resync_interval: float | None = None) -> ServiceWatcher:
        return ServiceWatcher(self, service, on_add, on_remove, on_update,
                              interval, resync_interval).start()
