"""Distributed lock + leader election over the coordination store.

Capability of the reference's etcd lock/election helpers
(pkg/master/etcd_client.go:100-131 — a lease-scoped lock key guards the
master role; losing the lease forfeits leadership, which is the
split-brain protection: a partitioned leader's writes stop mattering
once its lease expires), built on this store's primitives: the lock is
`put_if_absent(key, owner, lease)`, held exactly as long as the lease is
kept alive, and stolen by whoever's put_if_absent wins after expiry.

`DistributedLock` is the mutex; `LeaderElection` adds campaigning +
an `is_leader()` check callers must consult before privileged writes
(the fencing discipline: leadership is a lease-backed hint, so the
holder re-validates, exactly like the reference master re-checks its
etcd lease before serving).

r16 (edl-lint resource-lifecycle): LeaderElection grew close() —
resign + a deterministic join of the loss-watcher thread (resign
alone left the watcher to notice hold.stop within its poll period).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from edl_tpu.coord.store import Store
from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.coord.lock")


class EdlLockError(EdlError):
    pass


class _Hold:
    """One acquisition's private state (lease, liveness, keeper)."""

    __slots__ = ("lease", "last_renewal", "lost", "stop", "keeper")

    def __init__(self, lease: int, last_renewal: float):
        self.lease = lease
        self.last_renewal = last_renewal
        self.lost = threading.Event()
        self.stop = threading.Event()
        self.keeper: threading.Thread | None = None


class DistributedLock:
    """Lease-backed mutual exclusion on one store key.

    Args:
      store: coordination store (client or in-mem).
      key: lock key (namespace it, e.g. "/job/locks/master").
      owner: unique holder id (pod id); stored as the key's value so
        holders are observable and release is owner-checked.
      ttl: lease seconds; the keepalive thread refreshes at ttl/3. If the
        process dies, the lock frees itself after <= ttl.
    """

    def __init__(self, store: Store, key: str, owner: str, *,
                 ttl: float = 10.0):
        self.store = store
        self.key = key
        self.owner = owner
        self.ttl = ttl
        self._hold: _Hold | None = None

    # -- acquisition --------------------------------------------------------

    def try_acquire(self) -> bool:
        """One non-blocking attempt; True iff this owner now holds it."""
        cur = self.store.get(self.key)
        if cur is not None:
            return cur.value == self.owner and self.held()
        lease = self.store.lease_grant(self.ttl)
        if not self.store.put_if_absent(self.key, self.owner, lease):
            self.store.lease_revoke(lease)
            return False
        # Per-hold state object, captured by this hold's keeper thread: a
        # stale keeper from a previous hold (release() joins with a
        # timeout, so one can outlive release) mutates only ITS hold's
        # state, never the new acquisition's.
        hold = _Hold(lease=lease, last_renewal=time.monotonic())
        hold.keeper = threading.Thread(target=self._keepalive, args=(hold,),
                                       name=f"edl-lock-{self.key}",
                                       daemon=True)
        self._hold = hold
        hold.keeper.start()
        log.info("lock %s acquired by %s", self.key, self.owner)
        return True

    def acquire(self, timeout: float | None = None,
                poll: float = 0.2) -> bool:
        """Block (up to timeout) until acquired.

        Waiters subscribe to the lock key and wake on the holder's
        DELETE (release or lease expiry) instead of re-polling every
        `poll` seconds — handoff latency becomes event latency. The
        poll is kept as a TTL-derived fallback: with an in-process
        store and no sweeper thread, a dead holder's lease only expires
        on a store *call*, so a pure event wait could sleep forever.
        EDL_TPU_COORD_WATCH=0 restores the original fixed-poll loop.
        """
        from edl_tpu.coord.store import try_watch
        deadline = None if timeout is None else time.monotonic() + timeout
        watch = None
        try:
            while True:
                if self.try_acquire():
                    return True
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    return False
                if watch is None:
                    watch = try_watch(self.store, self.key)
                if watch is not None:
                    # fallback re-poll at a TTL-derived interval (see
                    # docstring); the DELETE event wakes us early
                    wait = max(poll, min(5.0, self.ttl / 2.0))
                    if deadline is not None:
                        wait = min(wait, deadline - now)
                    watch.get(timeout=max(wait, 0.0))
                else:
                    wait = poll if deadline is None \
                        else min(poll, deadline - now)
                    time.sleep(max(wait, 0.0))
        finally:
            if watch is not None:
                watch.cancel()

    # -- hold state ---------------------------------------------------------

    def held(self) -> bool:
        """True while this owner's lease-backed claim is PROVABLY live.

        Fencing: the answer is bounded by the last confirmed renewal's
        age, not by "no failure observed" — a stalled keepalive (GC
        pause, scheduler starvation, crashed thread) flips this False
        within ttl even though no loss event arrived, because by then the
        server may have expired the lease and elected someone else.
        Consult before every privileged action.
        """
        hold = self._hold
        return (hold is not None and not hold.lost.is_set()
                and time.monotonic() - hold.last_renewal < self.ttl)

    def _keepalive(self, hold: "_Hold") -> None:
        interval = max(0.05, self.ttl / 3.0)
        while not hold.stop.wait(interval):
            try:
                ok = self.store.lease_keepalive(hold.lease)
            except (EdlError, ConnectionError):
                ok = False
            if not ok:
                log.warning("lock %s: lease lost (owner %s)", self.key,
                            self.owner)
                hold.lost.set()
                return
            hold.last_renewal = time.monotonic()

    # -- release ------------------------------------------------------------

    def abandon(self) -> None:
        """Crash simulation (chaos tests): stop the keepalive WITHOUT
        revoking the lease, so the lock frees itself only when the TTL
        runs out — exactly what a killed holder's lock does. The hold
        is forgotten locally; a later try_acquire campaigns fresh."""
        hold, self._hold = self._hold, None
        if hold is None:
            return
        hold.stop.set()
        if hold.keeper is not None:
            hold.keeper.join(timeout=2)

    def release(self) -> None:
        hold, self._hold = self._hold, None
        if hold is None:
            return
        hold.stop.set()
        hold.keeper.join(timeout=2)
        if not hold.lost.is_set():
            # revoking OUR lease deletes only the key version attached to
            # it (etcd semantics) — a successor's lock, attached to its
            # own lease, is untouched, so this is inherently owner-checked
            try:
                self.store.lease_revoke(hold.lease)
            except (EdlError, ConnectionError):
                pass

    def __enter__(self) -> "DistributedLock":
        if not self.acquire():
            raise EdlLockError(f"could not acquire {self.key}")
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class LeaderElection:
    """Campaign for a leadership key; observe the current leader.

    Usage (the reference master pattern):
        election = LeaderElection(store, "/job/leader", pod_id)
        election.campaign()              # blocks until leader
        while election.is_leader():
            ... serve as master ...
    Followers call `leader()` to find who to talk to, and may pass
    `on_lost` to be notified when their own leadership lapses.
    """

    def __init__(self, store: Store, key: str, owner: str, *,
                 ttl: float = 10.0,
                 on_lost: Callable[[], None] | None = None):
        self.lock = DistributedLock(store, key, owner, ttl=ttl)
        self.store = store
        self.key = key
        self.owner = owner
        self._on_lost = on_lost
        self._watcher: threading.Thread | None = None

    def campaign(self, timeout: float | None = None) -> bool:
        ok = self.lock.acquire(timeout=timeout)
        if ok and self._on_lost is not None:
            self._watcher = threading.Thread(target=self._watch_lost,
                                             daemon=True)
            self._watcher.start()
        return ok

    def _watch_lost(self) -> None:
        # Poll held() rather than waiting on the loss event alone: a
        # stalled keepalive loses the lease without ever signalling. The
        # hold object is captured so a later re-campaign's new hold gets
        # its own watcher.
        hold = self.lock._hold
        if hold is None:
            return
        poll = max(0.05, self.lock.ttl / 4.0)
        while not hold.stop.wait(poll):
            if not self.lock.held() or self.lock._hold is not hold:
                if self._on_lost is not None and not hold.stop.is_set():
                    self._on_lost()
                return

    def is_leader(self) -> bool:
        return self.lock.held()

    def leader(self) -> str | None:
        rec = self.store.get(self.key)
        return rec.value if rec is not None else None

    def resign(self) -> None:
        self.lock.release()

    def close(self) -> None:
        """Teardown: resign (release joins the keepalive thread) and
        join the loss watcher. `resign` alone leaves the watcher to
        notice `hold.stop` within its poll period; close is the
        deterministic variant an owner's shutdown path wants (edl-lint
        resource-lifecycle)."""
        watcher, self._watcher = self._watcher, None
        self.resign()
        if watcher is not None:
            watcher.join(timeout=2.0)
