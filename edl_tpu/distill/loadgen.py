"""Open-loop load generation for the teacher serving plane.

The serving benches (tools/serve_load_bench.py, ``elastic_demo
--serve-load``, bench.py ``serving_throughput``) need an OPEN-loop
generator: arrival times come from a schedule alone, never from
completions. `TeacherClient` is the wrong tool for that twice over —
it is not thread-safe, and its ``max_inflight`` gate blocks the
submitter on slow responses, which silently converts the bench into a
closed loop and hides exactly the overload it is supposed to measure
(coordinated omission). This module ships its own minimal connection:
one send lock + one receiver thread per endpoint, submits never wait
on results, and latency is measured from the request's *scheduled*
arrival (a generator falling behind under load still charges the
delay to the server, not to the schedule).

Accounting is per priority class: offered / completed / shed / error
counts, latency quantiles, and SLO attainment (completed within the
SLO as a fraction of OFFERED — a shed or lost request counts against
its class). The event timeline backs the chaos assertions
(shed-then-recover, kill-then-recover) in the CI dryrun.

Rejections (``{"rejected": true, ...}``) are terminal here — an
open-loop bench measures shed offered load, it does not retry (the
reader's bounded retry ladder is exercised by its own tests). A dead
connection fails its in-flight requests, is dropped, and the next
arrival fails over to another live endpoint — the teacher-kill chaos
path.

Stdlib + numpy + tensor_wire only (no jax): the generator runs on
scheduler nodes and bare CI runners next to the pool it probes.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from collections import deque

import numpy as np

from edl_tpu.data import tensor_wire
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.loadgen")

DEFAULT_MIX = {"high": 0.2, "normal": 0.5, "low": 0.3}


def percentile(samples: list[float], q: float) -> float | None:
    """Nearest-rank percentile; None on no samples."""
    if not samples:
        return None
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))]


class LoadStats:
    """Thread-safe per-class accounting shared by every connection."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._counts: dict[str, dict[str, int]] = {}  # guarded-by: _lock
        self._lat_ms: dict[str, list[float]] = {}     # guarded-by: _lock
        # (t_rel, class, outcome) — outcome in {"ok", "shed", "error"}
        self.events: list[tuple[float, str, str]] = []  # guarded-by: _lock

    def _cls(self, cls: str) -> dict[str, int]:  # holds-lock: _lock
        return self._counts.setdefault(
            cls, {"offered": 0, "ok": 0, "shed": 0, "error": 0})

    def note_offered(self, cls: str) -> None:
        with self._lock:
            self._cls(cls)["offered"] += 1

    def note_done(self, cls: str, outcome: str,
                  latency_ms: float | None = None) -> None:
        t = self._clock() - self._t0
        with self._lock:
            self._cls(cls)[outcome] += 1
            if outcome == "ok" and latency_ms is not None:
                self._lat_ms.setdefault(cls, []).append(latency_ms)
            self.events.append((t, cls, outcome))

    # -- chaos oracles ---------------------------------------------------

    def first_event(self, outcome: str) -> float | None:
        with self._lock:
            ts = [t for t, _, o in self.events if o == outcome]
        return min(ts) if ts else None

    def ok_after(self, t: float, cls: str | None = None) -> int:
        """Completions after t — the recovery signal (work flows again
        after the first shed / after the chaos kill)."""
        with self._lock:
            return sum(1 for et, ec, o in self.events
                       if o == "ok" and et > t
                       and (cls is None or ec == cls))

    def summary(self, slo_ms: float | dict | None = None) -> dict:
        dur = max(self._clock() - self._t0, 1e-9)
        with self._lock:
            counts = {c: dict(v) for c, v in self._counts.items()}
            lat = {c: list(v) for c, v in self._lat_ms.items()}
        by_class: dict[str, dict] = {}
        all_lat: list[float] = []
        for cls, c in sorted(counts.items()):
            samples = lat.get(cls, [])
            all_lat.extend(samples)
            slo = (slo_ms.get(cls) if isinstance(slo_ms, dict)
                   else slo_ms)
            attained = (sum(1 for x in samples if x <= slo)
                        if slo is not None else None)
            by_class[cls] = {
                **c,
                "shed_pct": round(100.0 * c["shed"]
                                  / max(c["offered"], 1), 1),
                "p50_ms": percentile(samples, 0.5),
                "p95_ms": percentile(samples, 0.95),
                "attainment": (round(attained / max(c["offered"], 1), 4)
                               if attained is not None else None),
            }
        total = {k: sum(c[k] for c in counts.values())
                 for k in ("offered", "ok", "shed", "error")}
        return {
            "duration_s": round(dur, 2),
            **total,
            "rps_offered": round(total["offered"] / dur, 1),
            "rps_sustained": round(total["ok"] / dur, 1),
            "p50_ms": percentile(all_lat, 0.5),
            "p95_ms": percentile(all_lat, 0.95),
            "by_class": by_class,
        }


class _Conn:
    """One pipelined connection: sends under a lock, one receiver
    thread matching FIFO responses to the pending deque (the server
    answers strictly in request order per connection)."""

    def __init__(self, endpoint: str, stats: LoadStats, *,
                 timeout: float = 5.0, clock=time.monotonic):
        from edl_tpu.utils.net import split_endpoint
        host, port = split_endpoint(endpoint)
        self.endpoint = endpoint
        self._stats = stats
        self._clock = clock
        # lifecycle: long-lived(owned by the generator's conn pool;
        # closed on eviction/failure and in run_open_loop's finally)
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._pending: deque = deque()  # (t_sched, cls)  guarded-by: _lock
        self._dead = False              # guarded-by: _lock
        self._recv = threading.Thread(target=self._recv_loop, daemon=True,
                                      name=f"loadgen-recv-{endpoint}")
        self._recv.start()

    @property
    def alive(self) -> bool:
        with self._lock:
            return not self._dead

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def send(self, meta: dict, tensors: dict, cls: str,
             t_sched: float) -> bool:
        """False when the connection is (or just went) dead — the
        caller fails over; nothing was recorded for this request."""
        with self._lock:
            if self._dead:
                return False
            # enqueue BEFORE the bytes go out: the receiver may see the
            # response before send_tensors returns
            self._pending.append((t_sched, cls))
            try:
                tensor_wire.send_tensors(self._sock, meta, tensors)
                return True
            except (OSError, tensor_wire.TensorWireError):
                self._pending.pop()
                self._die_locked()
                return False

    def _recv_loop(self) -> None:
        while True:
            try:
                meta, _ = tensor_wire.recv_tensors(self._sock)
            except (OSError, tensor_wire.TensorWireError):
                with self._lock:
                    self._die_locked()
                return
            now = self._clock()
            with self._lock:
                if not self._pending:
                    continue  # late control response; ignore
                t_sched, cls = self._pending.popleft()
            if meta.get("rejected"):
                self._stats.note_done(cls, "shed")
            elif meta.get("ok"):
                self._stats.note_done(cls, "ok",
                                      (now - t_sched) * 1e3)
            else:
                self._stats.note_done(cls, "error")

    def _die_locked(self) -> None:  # holds-lock: _lock
        """Fail every in-flight request once; idempotent."""
        if self._dead:
            return
        self._dead = True
        while self._pending:
            _, cls = self._pending.popleft()
            self._stats.note_done(cls, "error")
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            self._die_locked()
        self._recv.join(timeout=2.0)


def run_open_loop(endpoints, *, duration_s: float, rps: float,
                  rows: int = 4, feature_dim: int = 4,
                  mix: dict[str, float] | None = None, tenants: int = 2,
                  seed: int = 0, poisson: bool = True,
                  conn_timeout: float = 5.0, drain_s: float = 2.0,
                  stats: LoadStats | None = None,
                  stop: threading.Event | None = None,
                  on_arrival=None) -> LoadStats:
    """Drive ``rps`` requests/sec of ``rows``-row predicts for
    ``duration_s`` against the pool and return the accounting.

    ``endpoints`` is a list of ``host:port`` strings or a zero-arg
    callable returning the CURRENT list (registry-backed: a drained or
    killed teacher drops out on the next refresh). Arrivals are Poisson
    (seeded) unless ``poisson=False`` (fixed spacing); each arrival
    picks its class from ``mix`` and its tenant round-robin, and tries
    up to two live endpoints before recording the request as an error
    (offered load is never silently un-offered). ``on_arrival(i, t)``
    is the chaos hook — the caller kills a teacher mid-run from it.
    """
    mix = dict(mix or DEFAULT_MIX)
    stats = stats or LoadStats()
    stop = stop or threading.Event()
    rng = random.Random(seed)
    classes = sorted(mix)
    weights = [mix[c] for c in classes]
    endpoints_fn = endpoints if callable(endpoints) else (lambda: endpoints)
    # one connection per (endpoint, class): the server completes each
    # connection's responses in request order, so classes sharing a
    # socket would head-of-line block high behind admitted low —
    # separate connections per class model separate tenant processes
    conns: dict[tuple[str, str], _Conn] = {}
    feed = {"x": np.zeros((rows, feature_dim), np.float32)}

    def conn_for(ep: str, cls: str) -> _Conn | None:
        key = (ep, cls)
        conn = conns.get(key)
        if conn is not None and conn.alive:
            return conn
        if conn is not None:
            conns.pop(key).close()
        try:
            # lifecycle: long-lived(pool-owned; closed on eviction + finally)
            conns[key] = _Conn(ep, stats, timeout=conn_timeout)
        except OSError:
            return None
        return conns[key]

    t0 = time.monotonic()
    t_next, sent, rr = 0.0, 0, 0
    try:
        while not stop.is_set():
            t_next += (rng.expovariate(rps) if poisson else 1.0 / rps)
            if t_next > duration_s:
                break
            delay = t0 + t_next - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if on_arrival is not None:
                on_arrival(sent, t_next)
            cls = rng.choices(classes, weights)[0]
            tenant = f"tenant{sent % max(tenants, 1)}"
            meta = {"op": "predict", "seq": sent, "tenant": tenant,
                    "priority": cls}
            stats.note_offered(cls)
            eps = endpoints_fn()
            delivered = False
            for attempt in range(2):
                if not eps:
                    break
                ep = eps[(rr + attempt) % len(eps)]
                conn = conn_for(ep, cls)
                # t_sched, not now: a generator running late still
                # charges the delay to the server (no coordinated
                # omission)
                if conn is not None and conn.send(meta, feed, cls,
                                                 t0 + t_next):
                    delivered = True
                    break
            rr += 1
            if not delivered:
                stats.note_done(cls, "error")
            sent += 1
        # grace for in-flight responses (bounded — a wedged teacher
        # fails its pending on close instead of hanging the bench)
        deadline = time.monotonic() + drain_s
        while (time.monotonic() < deadline
               and any(c.pending() for c in conns.values())):
            time.sleep(0.02)
    finally:
        for conn in conns.values():
            conn.close()
    return stats
