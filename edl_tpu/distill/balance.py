"""Client<->teacher assignment: the pure rebalance math.

Capability of the reference's ``Service.rebalance`` / ``BalanceTable``
(distill/balance_table.py:137-310): with C clients and S servers,

    server_cap = ceil(C / S)        -- max clients one server feeds
    client_cap = max(1, S // C)     -- max servers one client may use

excess links are broken, then clients are greedily linked to the
least-loaded eligible servers; a client's ``version`` bumps exactly when
its server set changes, so heartbeats can return deltas only.

Invariants (property-tested in tests/test_balance.py):

  I1. every server feeds at most ``server_cap`` clients;
  I2. every client holds at most ``client_cap`` servers;
  I3. when S > 0, every client holds exactly ``client_cap`` servers
      (capacity S*ceil(C/S) >= C always suffices);
  I4. server loads are balanced: max(load) - min(load) <= 1 whenever every
      server is eligible for every client — including after joins into a
      long-lived assignment (the skew-repair pass shifts links off the
      most-loaded servers, so a new teacher is put to work immediately
      instead of waiting for client churn);
  I5. versions bump iff the client's server set changed;
  I6. utilization is a TIE-BREAK only: among servers with equal link
      counts the least-busy is preferred — the busy score blends the
      registrar-reported ``util`` with ``queue_depth`` (each queued
      request adds ``QUEUE_WEIGHT``; with a per-class depth split the
      class-specific ``CLASS_QUEUE_WEIGHT`` applies instead, so queued
      HIGH-priority work repels new links hardest), so a backlogged
      teacher sheds new clients before it violates the latency SLO and
      the idle S mod C servers of an under-subscribed service are the
      busiest ones — I1-I4 are unaffected by construction (the link
      count stays the primary key).

Unlike the reference this is a standalone, lock-free-by-construction value
type: the discovery server owns one instance per service and serializes
access; nothing here touches the network or the store.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field


@dataclass
class ClientLinks:
    servers: tuple[str, ...] = ()
    version: int = 0
    last_seen: float = 0.0   # heartbeat bookkeeping (set by the owner)
    meta: dict = dc_field(default_factory=dict)


def caps(n_clients: int, n_servers: int) -> tuple[int, int]:
    """(server_cap, client_cap) for the given population."""
    if n_servers == 0 or n_clients == 0:
        return 0, 0
    server_cap = -(-n_clients // n_servers)          # ceil(C/S)
    client_cap = max(1, n_servers // n_clients)
    return server_cap, client_cap


class ServiceBalance:
    """Assignment state for one service name."""

    # Each queued request adds this much to the busy score: a teacher
    # with 5+ requests backed up loses every tie even against one
    # running flat-out with an empty queue — backlog is the leading
    # indicator of an SLO violation, utilization only the trailing one.
    QUEUE_WEIGHT = 0.2
    # With a per-priority-class depth split (r23 registrars), the same
    # backlog weighs by CLASS: queued high-priority work pressures the
    # tie-break hardest (that backlog is about to breach an SLO), queued
    # low-priority work barely at all (it sheds first under overload
    # anyway). Unknown classes fall back to QUEUE_WEIGHT.
    CLASS_QUEUE_WEIGHT = {"high": 0.4, "normal": 0.2, "low": 0.05}

    def __init__(self, name: str):
        self.name = name
        self.servers: tuple[str, ...] = ()
        self.clients: dict[str, ClientLinks] = {}
        # teacher-reported busy score (registrar stats `util`): ONLY a
        # tie-break among equal link counts, so I1-I4 are untouched —
        # when the population leaves servers idle (S mod C) or several
        # candidates tie, the LEAST-busy teachers get the links
        self.utilization: dict[str, float] = {}
        # reported intake backlog (registrar stats `queue_depth`),
        # blended into the same tie-break: a backlogged teacher sheds
        # NEW clients before it violates the latency SLO
        self.queue_depth: dict[str, int] = {}
        # per-class split of the same backlog (registrar
        # `queue_depth_by_class`): preferred over the flat depth when
        # present
        self.queue_depth_by_class: dict[str, dict[str, int]] = {}

    def set_utilization(self, util: dict[str, float],
                        queue_depth: dict[str, int] | None = None,
                        queue_depth_by_class:
                        dict[str, dict[str, int]] | None = None) -> None:
        self.utilization = dict(util)
        if queue_depth is not None:
            self.queue_depth = dict(queue_depth)
        if queue_depth_by_class is not None:
            self.queue_depth_by_class = dict(queue_depth_by_class)

    def _busy(self, server: str) -> float:
        # Unknown load is NEUTRAL (0.5), not idle: a non-reporting
        # teacher must not systematically win ties against one honestly
        # reporting a small util — it could be saturated for all we know.
        # Queue depth rides on top (unknown = 0: absence of a backlog
        # report must not outweigh a reported idle queue). A by-class
        # split, when reported, replaces the flat term with the
        # class-weighted one.
        by_class = self.queue_depth_by_class.get(server)
        if by_class:
            depth_term = sum(
                self.CLASS_QUEUE_WEIGHT.get(cls, self.QUEUE_WEIGHT) * n
                for cls, n in by_class.items())
        else:
            depth_term = self.QUEUE_WEIGHT * self.queue_depth.get(server, 0)
        return self.utilization.get(server, 0.5) + depth_term

    # -- membership --------------------------------------------------------

    def set_servers(self, servers: list[str]) -> bool:
        """Install the discovered teacher set. Returns True if it changed
        (caller should rebalance)."""
        new = tuple(sorted(set(servers)))
        if new == self.servers:
            return False
        self.servers = new
        return True

    def add_client(self, client_id: str, now: float = 0.0) -> bool:
        """Returns False if already present."""
        if client_id in self.clients:
            self.clients[client_id].last_seen = now
            return False
        self.clients[client_id] = ClientLinks(last_seen=now)
        return True

    def remove_client(self, client_id: str) -> bool:
        return self.clients.pop(client_id, None) is not None

    def touch(self, client_id: str, now: float) -> bool:
        links = self.clients.get(client_id)
        if links is None:
            return False
        links.last_seen = now
        return True

    def expire_clients(self, now: float, ttl: float) -> list[str]:
        """Drop clients whose heartbeat is older than ttl; returns them."""
        dead = [cid for cid, l in self.clients.items()
                if now - l.last_seen > ttl]
        for cid in dead:
            del self.clients[cid]
        return dead

    # -- the rebalance -----------------------------------------------------

    def rebalance(self) -> list[str]:
        """Recompute assignments. Returns the clients whose set changed."""
        server_cap, client_cap = caps(len(self.clients), len(self.servers))
        load = {s: 0 for s in self.servers}
        kept: dict[str, list[str]] = {}

        # Phase 1 — keep existing links that survive caps and membership
        # (minimizes churn: a client keeps its teachers across a rebalance
        # whenever legal).
        for cid in sorted(self.clients):
            links = []
            for s in self.clients[cid].servers:
                if s in load and load[s] < server_cap \
                        and len(links) < client_cap:
                    links.append(s)
                    load[s] += 1
            kept[cid] = links

        # Phase 2 — greedy fill to client_cap from least-loaded servers.
        for cid in sorted(self.clients):
            links = kept[cid]
            while len(links) < client_cap:
                candidates = [s for s in self.servers
                              if load[s] < server_cap and s not in links]
                if not candidates:
                    break
                best = min(candidates,
                           key=lambda s: (load[s], self._busy(s), s))
                links.append(best)
                load[best] += 1

        # Phase 3 — skew repair: without it I4 holds only for fresh
        # assignments — a teacher joining a long-lived service would sit
        # idle until client churn, because phase 1 keeps every legal old
        # link. Shift one link at a time from the most- to the
        # least-loaded server until the gap closes to <= 1.
        if self.servers:
            while True:
                lo = min(self.servers,
                         key=lambda s: (load[s], self._busy(s), s))
                hi = max(self.servers,
                         key=lambda s: (load[s], self._busy(s), s))
                if load[hi] - load[lo] <= 1:
                    break
                moved = False
                for cid in sorted(self.clients):
                    links = kept[cid]
                    if hi in links and lo not in links:
                        links[links.index(hi)] = lo
                        load[hi] -= 1
                        load[lo] += 1
                        moved = True
                        break
                if not moved:
                    break

        changed = []
        for cid, links in kept.items():
            entry = self.clients[cid]
            new = tuple(links)
            if set(new) != set(entry.servers):
                entry.servers = new
                entry.version += 1
                changed.append(cid)
            else:
                entry.servers = new  # order may differ; same set, no bump
        return changed

    # -- reads -------------------------------------------------------------

    def get(self, client_id: str) -> ClientLinks | None:
        return self.clients.get(client_id)

    def loads(self) -> dict[str, int]:
        out = {s: 0 for s in self.servers}
        for links in self.clients.values():
            for s in links.servers:
                if s in out:
                    out[s] += 1
        return out
