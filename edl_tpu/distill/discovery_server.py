"""Discovery/balancer daemon: assigns teacher servers to distill clients.

Capability of the reference's DiscoveryServicer + BalanceTable
(distill/discovery_server.py:28-100, distill/balance_table.py:331-613):

- students ``register`` under a service name and ``heartbeat`` every couple
  of seconds; responses carry their assigned teacher list as a versioned
  delta (servers included only when the client's version is stale);
- teacher membership comes from the coordination-store registry (written by
  ``edl_tpu.distill.registrar``); a tick thread re-reads it, expires silent
  clients, and rebalances;
- multiple discovery replicas register themselves under ``__balance__`` and
  shard service names over a consistent-hash ring: a request for a service
  owned by another replica gets ``REDIRECT`` + the owner endpoint
  (balance_table.py:363-433 REDIRECT sharding).

Wire: the store's framed-JSON protocol (coord/wire.py). Statuses: OK,
ALREADY_REGISTER, UNREGISTERED, REDIRECT (reference enum
protos/distill_discovery.proto:21-51).

CLI:
    python -m edl_tpu.distill.discovery_server --store 127.0.0.1:2379 \
        --port 23800
"""

from __future__ import annotations

import argparse
import json
import socket
import socketserver
import threading
import time

from edl_tpu.coord import wire
from edl_tpu.coord.redis_store import connect_store
from edl_tpu.coord.consistent_hash import ConsistentHash
from edl_tpu.coord.registry import Registration, ServiceRegistry
from edl_tpu.coord.store import Store
from edl_tpu.distill.balance import ServiceBalance
from edl_tpu.utils import net
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.discovery_server")

BALANCE_SERVICE = "__balance__"
DISTILL_ROOT = "edl_distill"


class BalanceTable:
    """All per-service assignment state of one discovery replica."""

    def __init__(self, store: Store, endpoint: str, *,
                 root: str = DISTILL_ROOT, client_ttl: float = 6.0,
                 clock=time.monotonic):
        self.registry = ServiceRegistry(store, root=root)
        self.endpoint = endpoint
        self.client_ttl = client_ttl
        self._clock = clock
        self._lock = threading.RLock()
        self._services: dict[str, ServiceBalance] = {}
        self._ring = ConsistentHash()

    # -- ownership (REDIRECT sharding) -------------------------------------

    def refresh_ring(self) -> None:
        metas = self.registry.get_service(BALANCE_SERVICE)
        nodes = [m.server for m in metas]
        with self._lock:
            # Always include ourselves: a replica must not redirect away
            # requests just because its own registration hasn't landed yet.
            if self.endpoint not in nodes:
                nodes.append(self.endpoint)
            self._ring.set_nodes(nodes)

    def owner_of(self, service: str) -> str:
        with self._lock:
            return self._ring.lookup(service) or self.endpoint

    def _redirect(self, service: str) -> dict | None:
        owner = self.owner_of(service)
        if owner != self.endpoint:
            return {"ok": True, "status": "REDIRECT", "leader": owner}
        return None

    # -- client RPCs --------------------------------------------------------

    def _apply_registry(self, svc: ServiceBalance, metas) -> None:
        """Install a registry snapshot: servers AND busy scores, then
        rebalance — one helper so register() and tick() cannot drift.
        The busy tie-break must be live from the FIRST assignment:
        phase-1 keep preserves whatever links a rebalance creates, so a
        util-blind initial fill would freeze name-order links past
        every later tick."""
        svc.set_servers([m.server for m in metas])
        svc.set_utilization(*self._busy_scores(metas))
        svc.rebalance()

    def register(self, client_id: str, service: str) -> dict:
        redirect = self._redirect(service)
        if redirect is not None:
            return redirect
        with self._lock:
            # registry read INSIDE the lock: serialized against tick(),
            # so a stale snapshot can never overwrite a fresher one
            # (spurious teacher-drop + double version bump otherwise)
            metas = self.registry.get_service(service)
            svc = self._services.setdefault(service, ServiceBalance(service))
            fresh = svc.add_client(client_id, self._clock())
            self._apply_registry(svc, metas)
            links = svc.get(client_id)
            status = "OK" if fresh else "ALREADY_REGISTER"
            log.info("client %s -> service %s (%s, %d teachers)", client_id,
                     service, status, len(links.servers))
            return {"ok": True, "status": status,
                    "servers": list(links.servers), "version": links.version}

    def heartbeat(self, client_id: str, service: str, version: int) -> dict:
        redirect = self._redirect(service)
        if redirect is not None:
            return redirect
        with self._lock:
            svc = self._services.get(service)
            if svc is None or not svc.touch(client_id, self._clock()):
                return {"ok": True, "status": "UNREGISTERED"}
            links = svc.get(client_id)
            if links.version != version:
                return {"ok": True, "status": "OK",
                        "servers": list(links.servers),
                        "version": links.version}
            return {"ok": True, "status": "OK"}

    def deregister(self, client_id: str, service: str) -> dict:
        with self._lock:
            svc = self._services.get(service)
            if svc is not None and svc.remove_client(client_id):
                svc.rebalance()
            return {"ok": True, "status": "OK"}

    # -- tick ---------------------------------------------------------------

    @staticmethod
    def _busy_scores(metas) -> tuple[dict[str, float], dict[str, int],
                                     dict[str, dict[str, int]]]:
        """Registrar-published busy fractions (`util`), intake backlogs
        (`queue_depth`) and their per-priority-class split
        (`queue_depth_by_class`) from the info JSON — the balancer's
        blended tie-break (balance.py invariant I6). Any field may be
        missing independently (old-format registrars)."""
        scores: dict[str, float] = {}
        depths: dict[str, int] = {}
        by_class: dict[str, dict[str, int]] = {}
        for m in metas:
            try:
                doc = json.loads(m.info)
            except (json.JSONDecodeError, TypeError):
                continue  # no/old-format info: neutral score
            if not isinstance(doc, dict):
                continue
            try:
                scores[m.server] = float(doc["util"])
            except (KeyError, TypeError, ValueError):
                pass
            try:
                depths[m.server] = int(doc["queue_depth"])
            except (KeyError, TypeError, ValueError):
                pass
            split = doc.get("queue_depth_by_class")
            if isinstance(split, dict):
                try:
                    by_class[m.server] = {str(c): int(n)
                                          for c, n in split.items()}
                except (TypeError, ValueError):
                    pass
        return scores, depths, by_class

    def tick(self) -> None:
        """Refresh teacher membership, expire silent clients, rebalance."""
        try:
            self.refresh_ring()
        except Exception as exc:
            log.warning("ring refresh failed: %s", exc)
        with self._lock:
            names = list(self._services)
        for name in names:
            with self._lock:
                svc = self._services.get(name)
                if svc is None:
                    continue
                try:
                    # read inside the lock (as register() does): the
                    # snapshot installed is never older than one a
                    # concurrent caller installed before us
                    metas = self.registry.get_service(name)
                except Exception as exc:
                    log.warning("teacher poll for %s failed: %s", name,
                                exc)
                    continue
                dead = svc.expire_clients(self._clock(), self.client_ttl)
                for cid in dead:
                    log.info("client %s expired from %s", cid, name)
                self._apply_registry(svc, metas)

    def stats(self) -> dict:
        with self._lock:
            names = list(self._services)
        # Teacher-reported utilization (registry `info`, published by the
        # registrar's stats loop) — the scheduler-facing performance view.
        info: dict[str, dict] = {}
        for name in names:
            try:
                info[name] = {m.server: m.info
                              for m in self.registry.get_service(name)}
            except Exception as exc:
                log.warning("utilization read for %s failed: %s", name, exc)
                info[name] = {}
        with self._lock:
            return {name: {"servers": list(svc.servers),
                           "clients": len(svc.clients),
                           "loads": svc.loads(),
                           "utilization": info.get(name, {})}
                    for name, svc in self._services.items()}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        table: BalanceTable = self.server.table  # type: ignore[attr-defined]
        sock: socket.socket = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                req = wire.recv_msg(sock)
            except (wire.WireError, OSError):
                return
            try:
                resp = self._dispatch(table, req)
            except Exception as exc:
                resp = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            try:
                wire.send_msg(sock, resp)
            except OSError:
                return

    @staticmethod
    def _dispatch(table: BalanceTable, req: dict) -> dict:
        op = req.get("op")
        if op == "register":
            return table.register(req["client"], req["service"])
        if op == "heartbeat":
            return table.heartbeat(req["client"], req["service"],
                                   int(req.get("version", -1)))
        if op == "deregister":
            return table.deregister(req["client"], req["service"])
        if op == "stats":
            return {"ok": True, "stats": table.stats()}
        if op == "ping":
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}


class _ThreadingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class DiscoveryServer:
    """In-process handle for a discovery replica (server + tick thread +
    self-registration under __balance__)."""

    def __init__(self, store: Store, *, port: int = 0,
                 host: str = "0.0.0.0", advertise: str | None = None,
                 root: str = DISTILL_ROOT, client_ttl: float = 6.0,
                 tick_interval: float = 1.0, lease_ttl: float = 10.0):
        self._server = _ThreadingServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        if advertise is None:
            # Loopback binds advertise loopback (local test topology);
            # everything else advertises the routable host IP.
            adv_host = host if host.startswith("127.") else net.host_ip()
            advertise = f"{adv_host}:{self.port}"
        self.endpoint = advertise
        self.table = BalanceTable(store, self.endpoint, root=root,
                                  client_ttl=client_ttl)
        self._server.table = self.table  # type: ignore[attr-defined]
        self._tick_interval = tick_interval
        self._lease_ttl = lease_ttl
        self._stop = threading.Event()
        self._registration: Registration | None = None

    def start(self) -> "DiscoveryServer":
        if self._registration is not None:   # idempotent (e.g. start() + with)
            return self
        self._registration = self.table.registry.register(
            BALANCE_SERVICE, self.endpoint, ttl=self._lease_ttl)
        self.table.refresh_ring()
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="discovery-serve").start()
        threading.Thread(target=self._ticker, daemon=True,
                         name="discovery-tick").start()
        log.info("discovery server %s up", self.endpoint)
        return self

    def _ticker(self) -> None:
        while not self._stop.wait(self._tick_interval):
            self.table.tick()

    def stop(self) -> None:
        self._stop.set()
        if self._registration is not None:
            self._registration.stop()
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.distill.discovery_server",
        description="Distill discovery/balancer daemon")
    parser.add_argument("--store", default="127.0.0.1:2379")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=23800)
    parser.add_argument("--advertise", default=None,
                        help="endpoint other hosts reach us at")
    parser.add_argument("--root", default=DISTILL_ROOT)
    parser.add_argument("--client-ttl", type=float, default=6.0)
    parser.add_argument("--tick-interval", type=float, default=1.0)
    args = parser.parse_args(argv)
    server = DiscoveryServer(
        connect_store(args.store), port=args.port, host=args.host,
        advertise=args.advertise, root=args.root,
        client_ttl=args.client_ttl, tick_interval=args.tick_interval)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
