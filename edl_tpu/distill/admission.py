"""Admission control for the teacher serving tier (r23).

Sits between the wire handlers and the Batcher's device pipeline: every
predict request passes ``AdmissionQueue.submit`` before it may occupy
intake. Three verdicts:

  * admitted — enqueued on the (priority class, tenant) flow; the
    batcher pops flows by weighted fair queueing (strict FIFO within a
    flow, virtual-time WFQ across flows, flow weight = its class
    weight), so one chatty tenant cannot starve the others and the high
    class drains ahead of low under contention;
  * rejected (queue-full) — the flow already holds ``queue_cap``
    requests. Bounded per-tenant queues are the memory/latency
    protection: past the cap the request is answered immediately with a
    typed retry-after instead of joining a collapsing backlog;
  * rejected (overload shed) — the class's estimated queue wait
    (backlog rows / measured service rate, scaled by the class's WFQ
    share) exceeds its delay budget. Budgets scale with class weight
    (``shed_ms`` is the NORMAL class budget), so under sustained
    overload the low class sheds first and the high class keeps its
    SLO — degradation per class, never global.

A rejection is a normal wire response ``{"ok": false, "rejected": true,
"retry_after_ms": R}`` — the connection stays open; `TeacherClient`
raises the typed `TeacherRejected` and the reader retries elsewhere
after a jittered backoff (reader.py).

Draining (`begin_drain`) flips every subsequent submit to a rejection
while already-admitted work drains normally — the piece that lets a
scale-down complete every in-flight request with zero hard kills
(scaler/serving.py drain protocol).

Pure stdlib + threading: no numpy, no jax — importable by wire-only
consumers and the load generator alike. doc/design_distill.md
("Continuous batching + admission control") is the design note.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from edl_tpu.utils.config import field, from_env

# Priority classes, highest first. Unknown class names degrade to
# "normal" instead of failing the request — an old client never breaks
# against a new server.
PRIORITIES = ("high", "normal", "low")
DEFAULT_CLASS_WEIGHTS = "high=4,normal=2,low=1"

# retry_after bounds (ms): never tell a client "come back in 0 ms"
# (thundering retry) nor park it for longer than a drain/resize takes.
RETRY_AFTER_MIN_MS = 25.0
RETRY_AFTER_MAX_MS = 2000.0

# service-rate estimation window; the overload rule stays disarmed until
# at least this many rows were served (a cold server never sheds on a
# garbage rate estimate).
RATE_WINDOW_S = 5.0
RATE_MIN_ROWS = 32


def parse_class_weights(spec: str) -> dict[str, float]:
    """``"high=4,normal=2,low=1"`` -> weight map (missing classes get
    weight 1; junk entries are ignored rather than fatal — this rides
    an env knob)."""
    weights = {c: 1.0 for c in PRIORITIES}
    for part in (spec or "").split(","):
        if "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            w = float(val)
        except ValueError:
            continue
        if name.strip() in weights and w > 0:
            weights[name.strip()] = w
    return weights


def normalize_priority(priority: str | None) -> str:
    p = (priority or "normal").strip().lower()
    return p if p in PRIORITIES else "normal"


@dataclass
class AdmissionConfig:
    """Knobs for the serving admission plane (env-overridable)."""
    # continuous: admit new requests into the forming device batch each
    # step; window: the r6 coalesce-window behavior (kept for A/B).
    batching: str = field("continuous", env="EDL_TPU_SERVE_BATCHING")
    # bounded per-(tenant, class) queue; past it submits reject.
    queue_cap: int = field(512, env="EDL_TPU_SERVE_ADMIT_CAP")
    # WFQ flow weights per priority class (also scales shed budgets).
    class_weights: str = field(DEFAULT_CLASS_WEIGHTS,
                               env="EDL_TPU_SERVE_CLASS_WEIGHTS")
    # delay budget of the NORMAL class in ms; other classes scale by
    # weight ratio (high waits longest before shedding). <= 0 disables
    # the overload-shed rule (the queue cap still bounds admission).
    shed_ms: float = field(0.0, env="EDL_TPU_SERVE_SHED_MS")

    @classmethod
    def from_env(cls, **overrides) -> "AdmissionConfig":
        return from_env(cls, **overrides)


class AdmissionReject(Exception):
    """Typed admission rejection: carries the retry-after hint that goes
    out on the wire verbatim."""

    def __init__(self, reason: str, retry_after_ms: float,
                 tenant: str = "default", priority: str = "normal"):
        super().__init__(f"admission rejected ({reason}): "
                         f"tenant={tenant} class={priority} "
                         f"retry_after_ms={retry_after_ms:.0f}")
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)
        self.tenant = tenant
        self.priority = priority


def _clamp_retry(ms: float) -> float:
    return min(max(ms, RETRY_AFTER_MIN_MS), RETRY_AFTER_MAX_MS)


class _Flow:
    """One (class, tenant) FIFO with its WFQ virtual finish time."""

    __slots__ = ("items", "vtime", "weight")

    def __init__(self, weight: float, vtime: float):
        self.items: deque = deque()
        self.vtime = vtime
        self.weight = weight


class AdmissionQueue:
    """Bounded multi-tenant intake replacing the Batcher's plain Queue.

    All state lives under one lock + condition; pops are O(#active
    flows) — flows are (class, tenant) pairs, a handful in practice.
    Items are opaque (the Batcher's _Request objects); this module knows
    only their row counts.
    """

    def __init__(self, config: AdmissionConfig | None = None, *,
                 clock=time.monotonic):
        self.config = config or AdmissionConfig()
        self._weights = parse_class_weights(self.config.class_weights)
        self._clock = clock
        self._cv = threading.Condition()
        self._flows: dict[tuple[str, str], _Flow] = {}  # guarded-by: _cv
        self._vclock = 0.0               # guarded-by: _cv
        self._rows_queued: dict[str, int] = {
            c: 0 for c in PRIORITIES}    # guarded-by: _cv
        self._n_queued = 0               # guarded-by: _cv
        self._admitted = 0               # guarded-by: _cv
        self._rejected = 0               # guarded-by: _cv
        self._rejected_by_class: dict[str, int] = {
            c: 0 for c in PRIORITIES}    # guarded-by: _cv
        self._rejected_by_reason: dict[str, int] = {}  # guarded-by: _cv
        self._served_window: deque = deque()  # (t, rows)  guarded-by: _cv
        self._draining = False           # guarded-by: _cv
        self._closed = False             # guarded-by: _cv

    # -- service-rate estimate (fed by the batcher's complete stage) ----

    def note_served(self, rows: int) -> None:
        now = self._clock()
        with self._cv:
            self._served_window.append((now, rows))
            self._trim_window(now)

    def _trim_window(self, now: float) -> None:
        w = self._served_window
        while w and now - w[0][0] > RATE_WINDOW_S:
            w.popleft()

    def _service_rate(self, now: float) -> float | None:
        """rows/s over the recent window; None until warmed up."""
        self._trim_window(now)
        if not self._served_window:
            return None
        rows = sum(r for _, r in self._served_window)
        if rows < RATE_MIN_ROWS:
            return None
        elapsed = max(now - self._served_window[0][0], 0.05)
        return rows / elapsed

    # -- admission ------------------------------------------------------

    def _budget_ms(self, cls: str) -> float:
        base = self.config.shed_ms
        return base * self._weights[cls] / self._weights["normal"]

    def _est_wait_ms(self, cls: str, rate: float) -> float:
        """Expected queue wait of a NEW arrival in ``cls``: the class's
        backlog divided by its WFQ share of the service rate. Classes
        with no backlog take no share (WFQ is work-conserving)."""
        active = [c for c in PRIORITIES if self._rows_queued[c] > 0
                  or c == cls]
        share = self._weights[cls] / sum(self._weights[c] for c in active)
        return self._rows_queued[cls] / max(rate * share, 1e-6) * 1e3

    def submit(self, item, rows: int, tenant: str = "default",
               priority: str = "normal") -> None:
        """Admit ``item`` or raise `AdmissionReject`. Never blocks."""
        cls = normalize_priority(priority)
        tenant = tenant or "default"
        now = self._clock()
        with self._cv:
            if self._closed or self._draining:
                self._count_reject(cls, "draining")
                raise AdmissionReject("draining", _clamp_retry(250.0),
                                      tenant, cls)
            key = (cls, tenant)
            flow = self._flows.get(key)
            if flow is not None and len(flow.items) >= self.config.queue_cap:
                rate = self._service_rate(now)
                hint = (self._est_wait_ms(cls, rate) if rate
                        else RETRY_AFTER_MAX_MS / 4)
                self._count_reject(cls, "queue-full")
                raise AdmissionReject("queue-full", _clamp_retry(hint),
                                      tenant, cls)
            if self.config.shed_ms > 0:
                rate = self._service_rate(now)
                if rate is not None:
                    wait_ms = self._est_wait_ms(cls, rate)
                    budget = self._budget_ms(cls)
                    if wait_ms > budget:
                        self._count_reject(cls, "overload")
                        raise AdmissionReject(
                            "overload", _clamp_retry(wait_ms - budget),
                            tenant, cls)
            if flow is None:
                # a newly-active flow starts at the current virtual
                # time, not its stale history — an idle flow must not
                # bank credit and then monopolize the scheduler
                flow = _Flow(self._weights[cls], self._vclock)
                self._flows[key] = flow
            flow.items.append((item, rows))
            self._rows_queued[cls] += rows
            self._n_queued += 1
            self._admitted += 1
            self._cv.notify()

    def _count_reject(self, cls: str, reason: str) -> None:  # holds-lock: _cv
        self._rejected += 1
        self._rejected_by_class[cls] += 1
        self._rejected_by_reason[reason] = (
            self._rejected_by_reason.get(reason, 0) + 1)

    # -- WFQ pop --------------------------------------------------------

    def _pop_locked(self):  # holds-lock: _cv
        best_key, best = None, None
        for key, flow in self._flows.items():
            if not flow.items:
                continue
            if best is None or flow.vtime < best.vtime:
                best_key, best = key, flow
        if best is None:
            return None
        item, rows = best.items.popleft()
        best.vtime += rows / best.weight
        self._vclock = max(self._vclock, best.vtime)
        cls = best_key[0]
        self._rows_queued[cls] -= rows
        self._n_queued -= 1
        if not best.items:
            # drop idle flows so the by-tenant stats dict stays bounded
            del self._flows[best_key]
        return item

    def get(self, timeout: float | None = None):
        """Next item by WFQ order; None on timeout or once closed."""
        deadline = (self._clock() + timeout) if timeout is not None else None
        with self._cv:
            while True:
                item = self._pop_locked()
                if item is not None:
                    return item
                if self._closed:
                    return None
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def get_nowait(self):
        with self._cv:
            return self._pop_locked()

    # -- lifecycle / introspection --------------------------------------

    def begin_drain(self) -> None:
        with self._cv:
            self._draining = True

    @property
    def draining(self) -> bool:
        with self._cv:
            return self._draining

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def qsize(self) -> int:
        with self._cv:
            return self._n_queued

    def stats(self) -> dict:
        """Counters merged into Batcher.stats() (flat + one-level dicts
        so the obs plane renders them as labeled gauges)."""
        with self._cv:
            by_class = {c: 0 for c in PRIORITIES}
            by_tenant: dict[str, int] = {}
            for (cls, tenant), flow in self._flows.items():
                n = len(flow.items)
                by_class[cls] += n
                by_tenant[tenant] = by_tenant.get(tenant, 0) + n
            return {
                "admitted_total": self._admitted,
                "rejected_total": self._rejected,
                "rejected_by_class": dict(self._rejected_by_class),
                "rejected_by_reason": dict(self._rejected_by_reason),
                "queue_depth_by_class": by_class,
                "queue_depth_by_tenant": by_tenant,
                "draining": int(self._draining),
            }
