"""Student-side discovery client: register, heartbeat, cached teacher list.

Capability of the reference's DiscoveryClient
(distill/discovery_client.py:47-253): registers with a discovery replica,
heartbeats on a background thread, follows REDIRECT to the shard owner,
re-registers after UNREGISTERED or connection loss, and caches the assigned
teacher list for lock-free reads by the distill pipeline.
"""

from __future__ import annotations

import socket
import threading

from edl_tpu.coord import wire
from edl_tpu.utils import net, unique_name
from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.discovery_client")


class EdlDiscoveryError(EdlError):
    pass


class DiscoveryClient:
    """One registration of this process under a distill service name.

    ``get_servers()`` is safe from any thread and never blocks on the
    network — it returns the last heartbeat's assignment.
    """

    def __init__(self, endpoints: str | list[str], service: str, *,
                 client_id: str | None = None, heartbeat_interval: float = 2.0,
                 timeout: float = 5.0, max_redirects: int = 8):
        if isinstance(endpoints, str):
            endpoints = [e for e in endpoints.split(",") if e]
        if not endpoints:
            raise EdlDiscoveryError("no discovery endpoints")
        self.endpoints = endpoints
        self.service = service
        self.client_id = client_id or unique_name.client_id()
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        self.max_redirects = max_redirects

        self._sock: socket.socket | None = None
        self._connected_to: str | None = None
        self._servers: tuple[str, ...] = ()
        self._version = -1
        self._ready = threading.Event()   # set on first assignment (even ())
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- wire helpers -------------------------------------------------------

    def _dial(self, endpoint: str) -> socket.socket:
        host, port = net.split_endpoint(endpoint)
        sock = socket.create_connection((host, port), timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._connected_to = None

    def _call(self, **req) -> dict:
        if self._sock is None:
            raise EdlDiscoveryError("not connected")
        wire.send_msg(self._sock, req)
        resp = wire.recv_msg(self._sock)
        if not resp.get("ok"):
            raise EdlDiscoveryError(resp.get("error", "discovery error"))
        return resp

    # -- registration (with REDIRECT chasing) ------------------------------

    def _register_once(self, endpoint: str) -> dict:
        """Register at `endpoint`, following REDIRECTs. Leaves _sock
        connected to the shard owner on success."""
        target = endpoint
        for _ in range(self.max_redirects):
            self._close()
            self._sock = self._dial(target)
            self._connected_to = target
            resp = self._call(op="register", client=self.client_id,
                              service=self.service)
            status = resp.get("status")
            if status in ("OK", "ALREADY_REGISTER"):
                return resp
            if status == "REDIRECT":
                target = resp["leader"]
                log.info("redirected to shard owner %s", target)
                continue
            raise EdlDiscoveryError(f"register got status {status}")
        raise EdlDiscoveryError(f"redirect loop after {self.max_redirects} hops")

    def _register_any(self) -> dict:
        last: Exception | None = None
        for endpoint in self.endpoints:
            try:
                return self._register_once(endpoint)
            except (OSError, wire.WireError, EdlError) as exc:
                last = exc
                log.warning("register via %s failed: %s", endpoint, exc)
        self._close()
        raise EdlDiscoveryError(f"all discovery endpoints failed: {last}")

    def _install(self, resp: dict) -> None:
        if "servers" in resp:
            servers = tuple(resp["servers"])
            if servers != self._servers:
                log.info("teacher set -> %s (v%s)", list(servers),
                         resp.get("version"))
            self._servers = servers
            self._version = int(resp.get("version", -1))
            self._ready.set()

    # -- lifecycle ----------------------------------------------------------

    def start(self, timeout: float = 30.0) -> "DiscoveryClient":
        resp = self._register_any()
        self._install(resp)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"discovery-hb-{self.service}")
        self._thread.start()
        if not self._ready.wait(timeout):
            raise EdlDiscoveryError("no assignment within start timeout")
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                resp = self._call(op="heartbeat", client=self.client_id,
                                  service=self.service,
                                  version=self._version)
            except (OSError, wire.WireError, EdlError) as exc:
                log.warning("heartbeat failed (%s); re-registering", exc)
                self._reconnect()
                continue
            status = resp.get("status")
            if status == "OK":
                self._install(resp)
            elif status in ("UNREGISTERED", "REDIRECT"):
                log.info("heartbeat got %s; re-registering", status)
                self._reconnect()

    def _reconnect(self) -> None:
        if self._stop.is_set():
            return
        try:
            self._version = -1   # force a full assignment on re-register
            resp = self._register_any()
            self._install(resp)
        except EdlError as exc:
            log.warning("re-register failed: %s", exc)

    # -- reads --------------------------------------------------------------

    def get_servers(self) -> list[str]:
        return list(self._servers)

    def wait_for_servers(self, timeout: float = 60.0,
                         poll: float = 0.1) -> list[str]:
        """Block until the assignment is non-empty (teachers exist)."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._servers:
                return list(self._servers)
            if self._stop.wait(poll):
                break
        raise EdlDiscoveryError(
            f"no teachers assigned for {self.service} within {timeout}s")

    def stop(self) -> None:
        self._stop.set()
        joined = True
        if self._thread is not None:
            # The heartbeat RPC's socket timeout (5s) outlives this join:
            # if the thread is still mid-RPC, writing a deregister frame on
            # the same socket would interleave with it, so skip the
            # courtesy deregister and let the lease TTL clean us up.
            self._thread.join(timeout=6.0)
            joined = not self._thread.is_alive()
        try:
            if self._sock is not None and joined:
                self._call(op="deregister", client=self.client_id,
                           service=self.service)
        except (OSError, wire.WireError, EdlError):
            pass
        self._close()
