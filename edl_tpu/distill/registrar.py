"""Teacher registrar: put a serving endpoint into the distill registry.

Capability of the reference's ServerRegister CLIs
(discovery/register.py:29-143 and distill/redis/server_register.py:20-136):
wait until the teacher server answers TCP, then register it under the
service name with a TTL lease; the Registration keeps the lease alive and
re-registers after expiry (bounded retries). Deregistration on stop.

With ``stats_interval > 0`` the registrar also polls the teacher's
``stats`` op and publishes rows/s + utilization into the registry ``info``
field — the "report job performance to the scheduler" data path the
reference reserves the field for (discovery/register.py:36-40,
doc/edl_collective_design_doc.md:28-31). Consumers read it from the
registry (ServerMeta.info) or the discovery server's ``stats`` op.

CLI (run next to each teacher server):
    python -m edl_tpu.distill.registrar --store 127.0.0.1:2379 \
        --service resnet_teacher --server 10.0.0.7:23900
"""

from __future__ import annotations

import argparse
import json
import threading
import time

from edl_tpu.coord.redis_store import connect_store
from edl_tpu.coord.registry import Registration, ServiceRegistry
from edl_tpu.coord.store import Store
from edl_tpu.utils import net
from edl_tpu.utils.backoff import Backoff
from edl_tpu.utils.exceptions import EdlRegisterError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.registrar")

DISTILL_ROOT = "edl_distill"


class TeacherRegistrar:
    """Probe-then-register lifecycle for one teacher endpoint."""

    def __init__(self, store: Store, service: str, server: str, *,
                 info: str = "", ttl: float = 10.0, root: str = DISTILL_ROOT,
                 probe_timeout: float = 60.0, probe_interval: float = 0.5,
                 stats_interval: float = 0.0):
        self.registry = ServiceRegistry(store, root=root)
        self.service = service
        self.server = server
        self.info = info
        self.ttl = ttl
        self.probe_timeout = probe_timeout
        self.probe_interval = probe_interval
        self.stats_interval = stats_interval
        self._registration: Registration | None = None
        self._stats_stop = threading.Event()
        self._stats_thread: threading.Thread | None = None
        self._last_stats: dict | None = None

    def wait_alive(self) -> None:
        # jittered-exponential probing (utils/backoff.py): a pool of
        # registrars waiting out one slow teacher must not re-probe in
        # lockstep, and the deadline keeps a never-up server a typed
        # error instead of a forever-wedge
        backoff = Backoff(base=self.probe_interval,
                          max_delay=max(self.probe_interval, 2.0))
        deadline = time.monotonic() + self.probe_timeout
        while time.monotonic() < deadline:
            if net.is_endpoint_alive(self.server):
                return
            backoff.sleep()
        raise EdlRegisterError(
            f"teacher {self.server} not answering after {self.probe_timeout}s")

    def start(self) -> "TeacherRegistrar":
        self.wait_alive()
        self._registration = self.registry.register(
            self.service, self.server, info=self.info, ttl=self.ttl)
        log.info("registered teacher %s under %s", self.server, self.service)
        if self.stats_interval > 0:
            self._stats_thread = threading.Thread(
                target=self._stats_loop, daemon=True,
                name=f"teacher-stats-{self.server}")
            self._stats_thread.start()
        return self

    # -- utilization publishing ---------------------------------------------

    def _poll_stats(self) -> dict | None:
        from edl_tpu.distill.teacher_server import TeacherClient
        try:
            client = TeacherClient(self.server, timeout=5.0)
        except OSError:
            return None
        try:
            return client.stats()
        except Exception:
            return None
        finally:
            client.close()

    def _utilization_info(self, cur: dict, prev: dict | None,
                          dt: float) -> str:
        from edl_tpu.distill.teacher_server import latency_quantile
        from edl_tpu.obs.metrics import Histogram
        d_rows = cur["served_rows"] - (prev or {}).get("served_rows", 0)
        d_busy = cur["busy_s"] - (prev or {}).get("busy_s", 0.0)
        # coalescing effectiveness over THIS window (mean device-batch
        # rows): a windowed delta like its siblings — a lifetime mean
        # would hide a teacher degrading to degenerate 1-request batches
        d_groups = (sum(cur.get("batch_rows_hist", {}).values())
                    - sum((prev or {}).get("batch_rows_hist", {}).values()))
        # latency over THIS window: difference the cumulative fixed-bucket
        # histograms (exact — the buckets line up by construction), so a
        # teacher going slow shows up within one stats interval instead
        # of being averaged away by its fast past. The SLO signal the
        # serving scaler consumes; null when the window served nothing.
        # The differencing is the shared obs Histogram primitive — the
        # same windowed-vs-cumulative contract the regression tests pin.
        d_lat = Histogram.window(cur.get("latency_hist_ms", {}),
                                 (prev or {}).get("latency_hist_ms", {}))
        # per-priority-class split of the same windowed signal (r23):
        # graceful degradation must be visible PER CLASS — a pool
        # shedding low while holding high's p95 looks healthy globally
        prev_by_cls = (prev or {}).get("latency_hist_ms_by_class", {})
        p95_by_class = {}
        for cls, hist in (cur.get("latency_hist_ms_by_class") or {}).items():
            p95 = latency_quantile(
                Histogram.window(hist, prev_by_cls.get(cls, {})), 0.95)
            if p95 is not None:
                p95_by_class[cls] = p95
        d_shed = (cur.get("rejected_total", 0)
                  - (prev or {}).get("rejected_total", 0))
        prev_rej = (prev or {}).get("rejected_by_class", {})
        shed_by_class = {
            cls: n - prev_rej.get(cls, 0)
            for cls, n in (cur.get("rejected_by_class") or {}).items()
            if n - prev_rej.get(cls, 0) > 0}
        return json.dumps({
            "rows_per_sec": round(d_rows / max(dt, 1e-9), 1),
            "util": round(min(1.0, d_busy / max(dt, 1e-9)), 3),
            "queue_depth": cur.get("queue_depth", 0),
            "inflight_groups": cur.get("inflight_groups", 0),
            "batch_rows_mean": round(d_rows / d_groups, 2) if d_groups
            else 0.0,
            "latency_ms_p50": latency_quantile(d_lat, 0.5),
            "latency_ms_p95": latency_quantile(d_lat, 0.95),
            "queue_depth_by_class": cur.get("queue_depth_by_class") or {},
            "latency_ms_p95_by_class": p95_by_class,
            "shed_per_sec": round(d_shed / max(dt, 1e-9), 2),
            "shed_by_class": shed_by_class,
            "draining": int(cur.get("draining", 0)),
        }, sort_keys=True)

    def _stats_loop(self) -> None:
        last_t = time.monotonic()
        while not self._stats_stop.wait(self.stats_interval):
            cur = self._poll_stats()
            now = time.monotonic()
            if cur is None or self._registration is None:
                continue
            try:
                info = self._utilization_info(cur, self._last_stats,
                                              now - last_t)
                self._registration.update_info(info)
            except Exception as exc:
                log.warning("utilization publish failed: %s", exc)
            self._last_stats, last_t = cur, now

    def stop(self, deregister: bool = True) -> None:
        self._stats_stop.set()
        if self._stats_thread is not None:
            self._stats_thread.join(timeout=2.0)
            self._stats_thread = None
        if self._registration is not None:
            self._registration.stop()
            self._registration = None
        if deregister:
            try:
                self.registry.deregister(self.service, self.server)
            except Exception as exc:
                log.warning("deregister failed: %s", exc)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.distill.registrar",
        description="Register a teacher inference server for discovery")
    parser.add_argument("--store", default="127.0.0.1:2379")
    parser.add_argument("--service", required=True)
    parser.add_argument("--server", required=True, help="host:port to expose")
    parser.add_argument("--info", default="",
                        help="opaque utilization/meta string")
    parser.add_argument("--ttl", type=float, default=10.0)
    parser.add_argument("--root", default=DISTILL_ROOT)
    parser.add_argument("--probe-timeout", type=float, default=60.0)
    parser.add_argument("--stats-interval", type=float, default=5.0,
                        help="seconds between utilization publishes "
                             "(0 disables)")
    args = parser.parse_args(argv)
    registrar = TeacherRegistrar(
        connect_store(args.store), args.service, args.server, info=args.info,
        ttl=args.ttl, root=args.root, probe_timeout=args.probe_timeout,
        stats_interval=args.stats_interval)
    registrar.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        registrar.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
