"""Teacher registrar: put a serving endpoint into the distill registry.

Capability of the reference's ServerRegister CLIs
(discovery/register.py:29-143 and distill/redis/server_register.py:20-136):
wait until the teacher server answers TCP, then register it under the
service name with a TTL lease; the Registration keeps the lease alive and
re-registers after expiry (bounded retries). Deregistration on stop.

CLI (run next to each teacher server):
    python -m edl_tpu.distill.registrar --store 127.0.0.1:2379 \
        --service resnet_teacher --server 10.0.0.7:23900
"""

from __future__ import annotations

import argparse
import threading
import time

from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.registry import Registration, ServiceRegistry
from edl_tpu.coord.store import Store
from edl_tpu.utils import net
from edl_tpu.utils.exceptions import EdlRegisterError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.registrar")

DISTILL_ROOT = "edl_distill"


class TeacherRegistrar:
    """Probe-then-register lifecycle for one teacher endpoint."""

    def __init__(self, store: Store, service: str, server: str, *,
                 info: str = "", ttl: float = 10.0, root: str = DISTILL_ROOT,
                 probe_timeout: float = 60.0, probe_interval: float = 0.5):
        self.registry = ServiceRegistry(store, root=root)
        self.service = service
        self.server = server
        self.info = info
        self.ttl = ttl
        self.probe_timeout = probe_timeout
        self.probe_interval = probe_interval
        self._registration: Registration | None = None

    def wait_alive(self) -> None:
        deadline = time.monotonic() + self.probe_timeout
        while time.monotonic() < deadline:
            if net.is_endpoint_alive(self.server):
                return
            time.sleep(self.probe_interval)
        raise EdlRegisterError(
            f"teacher {self.server} not answering after {self.probe_timeout}s")

    def start(self) -> "TeacherRegistrar":
        self.wait_alive()
        self._registration = self.registry.register(
            self.service, self.server, info=self.info, ttl=self.ttl)
        log.info("registered teacher %s under %s", self.server, self.service)
        return self

    def stop(self, deregister: bool = True) -> None:
        if self._registration is not None:
            self._registration.stop()
            self._registration = None
        if deregister:
            try:
                self.registry.deregister(self.service, self.server)
            except Exception as exc:
                log.warning("deregister failed: %s", exc)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.distill.registrar",
        description="Register a teacher inference server for discovery")
    parser.add_argument("--store", default="127.0.0.1:2379")
    parser.add_argument("--service", required=True)
    parser.add_argument("--server", required=True, help="host:port to expose")
    parser.add_argument("--info", default="",
                        help="opaque utilization/meta string")
    parser.add_argument("--ttl", type=float, default=10.0)
    parser.add_argument("--root", default=DISTILL_ROOT)
    parser.add_argument("--probe-timeout", type=float, default=60.0)
    args = parser.parse_args(argv)
    registrar = TeacherRegistrar(
        StoreClient(args.store), args.service, args.server, info=args.info,
        ttl=args.ttl, root=args.root, probe_timeout=args.probe_timeout)
    registrar.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        registrar.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
