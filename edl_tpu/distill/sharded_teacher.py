"""Multi-chip teacher serving: one server process drives ALL local chips.

The round-4 teacher served one chip per process; a pod-slice teacher
(v5e-8) then needed 8 processes and 8 registry entries. Here the teacher
forward is jitted over a LOCAL `jax.sharding.Mesh`: parameters land
tp/fsdp-sharded per the model's logical-axis annotations
(parallel/sharding.py rules — how an ERNIE-class teacher larger than one
chip's HBM is served at all), the batch splits over the data axes, and
XLA's SPMD partitioner materializes the collectives over ICI. One
process, one registry entry, N chips.

The reference's analogue is Paddle Serving's multi-card deployment
(README.md:74-92 serves the ERNIE teacher on multi-GPU hosts); the
redesign rides the same mesh machinery as training instead of a serving
framework.

Composes with the compressed wire (teacher_server.compress_outputs):
``serve_topk`` runs `lax.top_k` INSIDE the sharded jit — on a
vocab-parallel (tp) head XLA computes the distributed top-k before
anything crosses to host — and packs (idx, val) into ONE fp32 array so
latency-bound links pay a single device->host fetch.

Usage (library; the teacher_server CLI exposes --local-mesh for the
dp-replicated flavor):

    mesh = make_mesh(MeshSpec({"dp": 2, "tp": 4}))
    variables = init_sharded(lambda: model.init(...), mesh)
    predict, meta = sharded_predict_fn(
        lambda v, x: model.apply(v, x, train=False), variables, mesh,
        serve_topk=16, classes=1000)
    TeacherServer(predict, compressed_meta=meta).start()
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from edl_tpu.parallel import mesh as mesh_lib
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.sharded_teacher")


def sharded_predict_fn(apply_fn, variables, mesh: Mesh, *,
                       input_key: str = "image",
                       output_key: str = "logits",
                       batch_axes: tuple[str, ...] = ("dp", "fsdp"),
                       input_dtype=None,
                       serve_topk: int = 0,
                       classes: int | None = None):
    """Build a `TeacherServer` predict_fn over a local mesh.

    apply_fn(variables, x) -> logits (any rank; classes on the LAST
    axis). Returns ``(predict, compressed_meta)`` — meta is None without
    ``serve_topk``, else the announcement TeacherServer attaches so
    dense clients scatter-expand transparently.

    Request rows need not divide the data axes: the batch pads to the
    next multiple (rows beyond the caller's are dropped after the
    forward), so the Batcher's power-of-two buckets and ragged tails
    both serve.
    """
    axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    data_sharding = mesh_lib.data_sharding(mesh, axes or None)
    dp = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
    if serve_topk and classes is None:
        raise ValueError("serve_topk needs `classes` (the dense width) "
                         "for the client-side expansion announcement")
    if serve_topk and serve_topk > classes:
        # lax.top_k rejects k > axis size — clamp instead of an opaque
        # XLA error on the first predict (same guard as the CLI path)
        log.warning("serve_topk %d > %d classes; clamping", serve_topk,
                    classes)
        serve_topk = int(classes)

    @jax.jit
    def fwd(variables, x):
        logits = apply_fn(variables, x)
        if not serve_topk:
            return logits
        val, idx = lax.top_k(logits.astype(jnp.float32), serve_topk)
        # ONE packed fp32 fetch (see bench.py's tunnel finding: two tiny
        # device->host pulls cost more than one small one)
        idx_bits = lax.bitcast_convert_type(idx.astype(jnp.int32),
                                            jnp.float32)
        return jnp.concatenate([idx_bits, val], axis=-1)

    def predict(feeds: dict) -> dict:
        x = np.asarray(feeds[input_key])
        if input_dtype is not None:
            x = x.astype(input_dtype)
        rows = x.shape[0]
        pad = (-rows) % dp
        if pad:
            x = np.concatenate(
                [x, np.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
        placed = jax.device_put(x, data_sharding)
        out = np.asarray(fwd(variables, placed))[:rows]
        if not serve_topk:
            return {output_key: out.astype(np.float32)}
        idx = np.ascontiguousarray(out[..., :serve_topk]).view(np.int32)
        val = out[..., serve_topk:].astype(np.float16)
        return {output_key + ".idx": idx, output_key + ".val": val}

    meta = None
    if serve_topk:
        meta = {output_key: {"topk": serve_topk, "classes": int(classes),
                             "values": "<f2"}}
    log.info("sharded teacher predict over mesh %s (data axes %s, x%d)",
             dict(mesh.shape), axes, dp)
    return predict, meta


def parse_local_mesh(spec: str) -> Mesh:
    """``"dp=4,tp=2"`` -> a local-device Mesh (teacher CLI flag)."""
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return mesh_lib.make_mesh(mesh_lib.MeshSpec(axes))
