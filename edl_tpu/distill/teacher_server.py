"""JAX teacher inference server: batched forward serving over tensor wire.

The TPU-native stand-in for the reference's Paddle Serving teacher
(README.md:74-92; students call it through distill_worker.py:203-226). One
process drives the local TPU chips; a coalescing batcher concatenates
concurrent client requests into one device batch and pads to a fixed
bucket so XLA compiles once per bucket (static shapes — no recompiles on
ragged tails). This coalescing is what Paddle Serving gave the reference
for free and SURVEY.md §7 flags as a hard part of hitting ≥1500 img/s.

Protocol (tensor_wire frames):
    request  meta {"op": "predict"}          tensors {feed_name: array}
    response meta {"ok": true}               tensors {fetch_name: array}
    request  meta {"op": "ping"}             -> {"ok": true}, no tensors
Requests may carry {"seq": n}; the response echoes it. Responses on one
connection come back strictly in request order, and the server does NOT
wait for a predict to finish before reading the next request — clients
may pipeline many requests per connection (TeacherClient.predict_async).

Wire compression (two independent levers; see `compress_outputs`):
  - client-negotiated: request meta carries {"compress": {"topk": K,
    "values": "float16"}} and eligible dense outputs come back as
    name.idx/name.val with meta {"compressed": {name: {...}}};
  - server-side device top-k: predict_fn emits name.idx/name.val
    directly (lax.top_k before the host transfer, CLI --serve-topk);
    the server announces the same meta from `compressed_meta`.
  Dense clients scatter-expand transparently (`expand_outputs`); sparse
  clients (TeacherClient(expand=False)) consume idx/val as-is with
  train/classification.py `make_sparse_distill_step`. Feeds travel in
  the caller's dtype — send uint8 images and normalize teacher-side for
  a 4x cheaper request direction.

CLI (serves a zoo model with random or checkpointed params):
    python -m edl_tpu.distill.teacher_server --model mlp --port 23900

r16 (edl-lint guarded-by): the Batcher's shared counters are annotated
``# guarded-by: _stats_lock`` and machine-checked; the checker's first
dry run caught ``_window_ema_s`` being updated by the coalesce thread
OUTSIDE the lock while ``stats()`` read it under the lock — the EMA
update now takes ``_stats_lock``.
"""

from __future__ import annotations

import argparse
import queue
import socket
import socketserver
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from edl_tpu.data import tensor_wire
from edl_tpu.distill.admission import (PRIORITIES, AdmissionConfig,
                                       AdmissionQueue, AdmissionReject,
                                       normalize_priority)
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.teacher_server")


DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

# Fixed-bucket per-request latency histogram edges (ms, upper bounds;
# final bucket is open-ended). Fixed buckets — not a reservoir — so the
# registrar can difference two cumulative snapshots into an exact
# windowed histogram and quantiles never drift under load. The pattern
# generalized into the shared obs Histogram type (obs/metrics.py);
# these edges are the obs plane's canonical log ladder.
LATENCY_BUCKETS_MS = obs_metrics.LOG_BUCKETS_MS


def latency_quantile(hist_ms: dict, q: float) -> float | None:
    """q-quantile of a ``{bucket_upper_ms: count}`` histogram (keys may
    be str off the wire). Answers with the bucket's UPPER edge —
    conservative: the reported p95 is never below the true one, so an
    SLO decision made on it never under-provisions. None when empty.
    (Shim over the shared obs Histogram quantile.)"""
    return obs_metrics.Histogram.quantile(hist_ms, q)


def pad_to_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n  # beyond the largest bucket: serve exact (rare, recompiles)


@dataclass
class _Request:
    tensors: dict[str, np.ndarray]
    rows: int
    done: threading.Event = field(default_factory=threading.Event)
    result: dict[str, np.ndarray] | None = None
    error: str | None = None
    tenant: str = "default"
    cls: str = "normal"   # priority class (admission.PRIORITIES)
    # submit time: the latency histogram measures submit -> results
    # ready (coalesce wait + device compute + host fetch) — what a
    # pipelined client experiences per request, the serving SLO signal
    t_submit: float = field(default_factory=time.monotonic)


class Batcher:
    """Coalesce concurrent predict requests into padded device batches.

    Staged pipeline (r6): three threads connected by bounded queues so
    the chip never waits on host work —

        coalesce  — collect + concatenate + pad the next group while the
                    chip computes the current one (adaptive window below);
        compute   — calls predict_fn; with an async-dispatch backend
                    (jitted JAX) the call returns device arrays without
                    blocking, so the thread immediately feeds the chip
                    the NEXT coalesced batch;
        complete  — fetches outputs to host (np.asarray = the device->host
                    sync), slices per request, sets done. Overlaps the
                    transfer of batch N with the compute of batch N+1.

    (De)serialization and `compress_outputs` run on the per-connection
    handler/writer threads (see `_Handler`), never here.

    Batching modes (r23, ``EDL_TPU_SERVE_BATCHING``):

    ``continuous`` (default) — iteration-level admission, no timed
    window. A group dispatches the moment the pipeline can take it
    (idle-device latency is one queue hop), and while the pipeline is
    full the forming group keeps ADMITTING newly-arrived requests up to
    ``max_batch`` rows — each device step starts from everything that
    arrived during the previous one, the Orca/vLLM scheduling shape.
    ``max_wait`` is unused; ``max_wait_cap`` only bounds how long one
    group may keep forming against a saturated pipeline.

    ``window`` — the r6 adaptive coalescing window, kept for A/B
    benches: a group closes after ``max_wait`` ONLY when the device
    pipeline is idle, extending up to ``max_wait_cap`` while a previous
    group is in flight.

    Intake is an `AdmissionQueue` (bounded multi-tenant WFQ): submits
    may raise `AdmissionReject`, which the wire handler answers with a
    typed retry-after response instead of queuing toward a collapsed
    p95. See edl_tpu/distill/admission.py.
    """

    def __init__(self, predict_fn, *, max_batch: int = 64,
                 max_wait: float = 0.002,
                 buckets: tuple[int, ...] = DEFAULT_BUCKETS,
                 max_wait_cap: float | None = None,
                 stage_depth: int = 2,
                 batching: str | None = None,
                 admission: AdmissionConfig | None = None):
        self.predict_fn = predict_fn
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.max_wait_cap = (max_wait_cap if max_wait_cap is not None
                             else max(8 * max_wait, 0.016))
        self.buckets = tuple(sorted(buckets))
        self.admission_config = admission or AdmissionConfig.from_env()
        self.batching = batching or self.admission_config.batching
        if self.batching not in ("continuous", "window"):
            raise ValueError(f"unknown batching mode {self.batching!r}")
        self._q = AdmissionQueue(self.admission_config)
        # bounded stage queues: coalesce may run at most `stage_depth`
        # groups ahead of the chip, the chip at most `stage_depth` ahead
        # of the host fetch
        self._compute_q: queue.Queue = queue.Queue(maxsize=stage_depth)
        self._post_q: queue.Queue = queue.Queue(maxsize=stage_depth)
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._run_coalesce, daemon=True,
                             name="teacher-coalesce"),
            threading.Thread(target=self._run_compute, daemon=True,
                             name="teacher-compute"),
            threading.Thread(target=self._run_complete, daemon=True,
                             name="teacher-complete"),
        ]
        # adaptive-window state: groups currently past coalesce (queued,
        # computing, or fetching) — the "device busy" signal; plus an EMA
        # of realized window lengths for observability. All mutated from
        # three stage threads + read by the registrar's stats scrape, so
        # every field below is annotated for the guarded-by checker.
        self._stats_lock = threading.Lock()
        self._groups_inflight = 0    # guarded-by: _stats_lock
        self._window_ema_s = max_wait  # guarded-by: _stats_lock
        self._carry: _Request | None = None  # coalesce-thread-only
        # Cumulative utilization counters (the registry `info` data source:
        # reference discovery/register.py:36-40 reserves the field for
        # "report job performance to the scheduler").
        self._served_rows = 0        # guarded-by: _stats_lock
        self._served_requests = 0    # guarded-by: _stats_lock
        self._busy_s = 0.0           # guarded-by: _stats_lock
        # interval-union accounting across stages
        self._busy_until = 0.0       # guarded-by: _stats_lock
        self._started_at = time.monotonic()
        # intake high-water mark: observed demand
        self._pending_hwm = 0        # guarded-by: _stats_lock
        # Coalescing histogram: device-batch ROW count (pre-padding) ->
        # number of served groups. Whether concurrent client requests
        # actually merge (vs degenerate 1-request batches) is THE
        # efficiency question for a serving pool; the histogram makes it
        # observable instead of inferred.
        self._batch_hist: dict[int, int] = {}  # guarded-by: _stats_lock
        # Per-request latency histogram (fixed buckets, cumulative):
        # the SLO signal the serving scaler consumes. The shared obs
        # Histogram type (its own leaf lock; _stats_lock still orders
        # it against the sibling counters so one stats() snapshot is
        # coherent). inf = overflow.
        self._lat_hist = obs_metrics.Histogram(
            LATENCY_BUCKETS_MS)         # guarded-by: _stats_lock
        # per-priority-class split of the same signal: the registrar
        # differences these into windowed per-class p95 so graceful
        # degradation is observable PER CLASS, not globally
        self._lat_hist_by_class = {
            c: obs_metrics.Histogram(LATENCY_BUCKETS_MS)
            for c in PRIORITIES}        # guarded-by: _stats_lock

    def start(self) -> "Batcher":
        for t in self._threads:
            t.start()
        return self

    def submit(self, tensors: dict[str, np.ndarray], *,
               tenant: str = "default", priority: str = "normal"
               ) -> _Request:
        """Admit one predict request. Raises `AdmissionReject` when the
        tenant's queue is full, the class's delay budget is blown, or
        the batcher is draining — the caller answers with a typed
        retry-after instead of queueing."""
        rows = next(iter(tensors.values())).shape[0] if tensors else 0
        req = _Request(tensors=tensors, rows=rows, tenant=tenant or
                       "default", cls=normalize_priority(priority))
        self._q.submit(req, rows, req.tenant, req.cls)
        depth = self._q.qsize()
        if depth > self._pending_hwm:
            with self._stats_lock:
                self._pending_hwm = max(self._pending_hwm, depth)
        return req

    def begin_drain(self) -> None:
        """Stop admitting (every new submit rejects with retry-after)
        while already-admitted work completes normally — the graceful
        half of the scaler's drain protocol."""
        self._q.begin_drain()

    def _join(self, group: list[_Request], names: list[str], rows: int,
              req: _Request | None) -> tuple[int, bool]:
        """Try to add ``req`` to the forming group; heterogeneous feeds
        or row overflow OPEN the next group via carry (order
        preserved). Returns (rows, keep_collecting)."""
        if req is None:
            return rows, True
        if list(req.tensors) != names or rows + req.rows > self.max_batch:
            self._carry = req
            return rows, False
        group.append(req)
        return rows + req.rows, True

    def _collect(self) -> list[_Request]:
        if self.batching == "continuous":
            return self._collect_continuous()
        return self._collect_window()

    def _collect_continuous(self) -> list[_Request]:
        """Iteration-level admission: dispatch as soon as the pipeline
        has room, and while it has none keep admitting arrivals into
        the forming group — each device step starts from everything
        that arrived during the last one."""
        first = self._carry
        self._carry = None
        if first is None:
            first = self._q.get(timeout=0.2)
            if first is None:
                return []
        t_first = time.monotonic()
        hard = t_first + self.max_wait_cap
        names = list(first.tensors)
        group, rows = [first], first.rows
        while rows < self.max_batch:
            req = self._q.get_nowait()
            if req is not None:
                rows, more = self._join(group, names, rows, req)
                if not more:
                    break
                continue
            # intake empty: dispatch now unless the pipeline is full —
            # then the chip could not take the group anyway, so keep
            # admitting until a slot frees (bounded by max_wait_cap)
            if not self._compute_q.full() or self._stop.is_set() \
                    or time.monotonic() >= hard:
                break
            req = self._q.get(timeout=0.001)
            rows, more = self._join(group, names, rows, req)
            if not more:
                break
        window = time.monotonic() - t_first
        with self._stats_lock:
            self._window_ema_s += 0.2 * (window - self._window_ema_s)
        return group

    def _collect_window(self) -> list[_Request]:
        """r6 behavior: one blocking pop, then drain whatever arrives
        within the adaptive window (bounded by max_batch rows)."""
        first = self._carry
        self._carry = None
        if first is None:
            first = self._q.get(timeout=0.2)
            if first is None:
                return []
        t_first = time.monotonic()
        soft = t_first + self.max_wait
        hard = t_first + self.max_wait_cap
        names = list(first.tensors)
        group, rows = [first], first.rows
        while rows < self.max_batch:
            now = time.monotonic()
            if now >= hard:
                break
            with self._stats_lock:
                busy = self._groups_inflight > 0
            if now >= soft and not busy:
                break   # device idle: dispatching NOW starts work
            # device busy: the chip can't take this group yet, so keep
            # coalescing (1 ms polls re-check the busy signal)
            timeout = min((hard if busy else soft) - now, 0.001)
            req = self._q.get(timeout=max(timeout, 0.0))
            if req is None:
                if self._stop.is_set():
                    break
                continue
            rows, more = self._join(group, names, rows, req)
            if not more:
                break
        window = time.monotonic() - t_first
        with self._stats_lock:
            self._window_ema_s += 0.2 * (window - self._window_ema_s)
        return group

    def _fail_group(self, group: list[_Request], exc: Exception) -> None:
        log.exception("batch predict failed")
        for req in group:
            req.error = f"{type(exc).__name__}: {exc}"
            req.done.set()

    def _run_coalesce(self) -> None:
        while not self._stop.is_set():
            group = self._collect()
            if not group:
                continue
            names = list(group[0].tensors)
            rows = sum(g.rows for g in group)
            bucket = pad_to_bucket(rows, self.buckets)
            try:
                feeds = {}
                for name in names:
                    cat = np.concatenate([g.tensors[name] for g in group],
                                         axis=0)
                    if bucket > rows:
                        pad = np.zeros((bucket - rows,) + cat.shape[1:],
                                       cat.dtype)
                        cat = np.concatenate([cat, pad], axis=0)
                    feeds[name] = cat
            except Exception as exc:  # ragged feeds etc.
                self._fail_group(group, exc)
                continue
            with self._stats_lock:
                self._groups_inflight += 1
            self._compute_q.put((group, feeds, rows))
        self._compute_q.put(None)

    def _group_left(self) -> None:
        with self._stats_lock:
            self._groups_inflight -= 1

    def _run_compute(self) -> None:
        while True:
            item = self._compute_q.get()
            if item is None:
                break
            group, feeds, rows = item
            t0 = time.monotonic()
            try:
                outs = self.predict_fn(feeds)
            except Exception as exc:
                self._fail_group(group, exc)
                self._group_left()
                continue
            self._post_q.put((group, outs, rows, t0))
        self._post_q.put(None)

    def _run_complete(self) -> None:
        while True:
            item = self._post_q.get()
            if item is None:
                break
            group, outs, rows, t0 = item
            try:
                # the device->host fetch; predict_fn may return device
                # arrays (async dispatch) so the chip is already on the
                # next batch while this blocks
                outs = {k: np.asarray(v) for k, v in outs.items()}
            except Exception as exc:
                self._fail_group(group, exc)
                self._group_left()
                continue
            now = time.monotonic()
            with self._stats_lock:
                # union of [t0, now] intervals: overlapped stages must not
                # double-count device busy time
                self._busy_s += max(0.0, now - max(t0, self._busy_until))
                self._busy_until = now
                self._served_rows += rows
                self._served_requests += len(group)
                self._batch_hist[rows] = self._batch_hist.get(rows, 0) + 1
                for req in group:
                    lat_ms = (now - req.t_submit) * 1e3
                    self._lat_hist.observe(lat_ms)
                    self._lat_hist_by_class[req.cls].observe(lat_ms)
                self._groups_inflight -= 1
            # feed the admission plane's service-rate estimate (its own
            # leaf lock; never taken with _stats_lock held)
            self._q.note_served(rows)
            offset = 0
            for req in group:
                req.result = {k: v[offset:offset + req.rows]
                              for k, v in outs.items()}
                offset += req.rows
                req.done.set()

    def stats(self) -> dict:
        """Cumulative serving counters (consumed by TeacherRegistrar).

        The un-suffixed keys are a PINNED contract (the r15 autoscaler
        and drain poller consume queue_depth / inflight_groups / the
        latency quantiles; tests/test_serving_continuous.py pins the
        schema). ``*_by_class`` / ``*_by_tenant`` keys are one-level
        dicts the obs plane renders as labeled gauges."""
        # admission snapshot first (its own leaf lock — the two locks
        # are never nested, in either order)
        adm = self._q.stats()
        with self._stats_lock:
            hist = dict(sorted(self._batch_hist.items()))
            groups = sum(hist.values())
            rows_mean = (sum(r * c for r, c in hist.items()) / groups
                         if groups else 0.0)
            lat = self._lat_hist.snapshot()  # ascending edges, inf last
            lat_by_class = {c: h.snapshot()
                            for c, h in self._lat_hist_by_class.items()}
            out = {"served_rows": self._served_rows,
                   "served_requests": self._served_requests,
                   "busy_s": round(self._busy_s, 4),
                   "uptime_s": round(time.monotonic() - self._started_at, 4),
                   "queue_depth": self._q.qsize(),
                   # groups past intake (queued/computing/fetching): with
                   # queue_depth == 0 this is the whole "work still in
                   # flight" signal a draining pool waits out
                   "inflight_groups": self._groups_inflight,
                   "pending_hwm": self._pending_hwm,
                   "batching": self.batching,
                   "coalesce_window_ms": round(self._window_ema_s * 1e3,
                                               3),
                   # JSON object keys are strings on the wire
                   "batch_rows_hist": {str(r): c for r, c in hist.items()},
                   "batch_rows_mean": round(rows_mean, 2),
                   "latency_hist_ms": {str(b): c for b, c in lat.items()},
                   "latency_ms_p50": latency_quantile(lat, 0.5),
                   "latency_ms_p95": latency_quantile(lat, 0.95)}
        out.update(adm)
        out["latency_hist_ms_by_class"] = {
            c: {str(b): n for b, n in snap.items()}
            for c, snap in lat_by_class.items()}
        p95s = {c: latency_quantile(snap, 0.95)
                for c, snap in lat_by_class.items()}
        out["latency_ms_p95_by_class"] = {
            c: v for c, v in p95s.items() if v is not None}
        return out

    def stop(self) -> None:
        self._stop.set()
        self._q.close()
        for t in self._threads:
            t.join(timeout=5.0)


def compress_outputs(outs: dict[str, np.ndarray], spec: dict
                     ) -> tuple[dict, dict[str, np.ndarray]]:
    """Top-k + narrow-dtype compression of eligible prediction tensors.

    ``spec`` = ``{"topk": K, "values": "float16"}`` (client-negotiated
    per request). A 2-D floating (rows, classes) tensor with classes > K
    becomes ``name.idx`` (uint16 when classes fit, else int32; sorted by
    descending value) + ``name.val`` (K values in the narrow dtype);
    everything else passes through unchanged. Returns a meta fragment
    ``{"compressed": {name: {topk, classes, values}}}`` the client uses
    to expand — at 1000 classes and K=8 this turns 4000 B/row of fp32
    logits into 32 B/row, the lever the reference got from Paddle
    Serving's fetch-var selection (distill_worker.py:203-226).
    """
    k = int(spec.get("topk", 0))
    vdt = np.dtype(spec.get("values", "float16"))
    compressed: dict[str, dict] = {}
    out: dict[str, np.ndarray] = {}
    for name, arr in outs.items():
        if not (k > 0 and arr.ndim == 2 and arr.shape[1] > k
                and np.issubdtype(arr.dtype, np.floating)):
            out[name] = arr
            continue
        idx = np.argpartition(arr, -k, axis=1)[:, -k:]
        vals = np.take_along_axis(arr, idx, axis=1)
        order = np.argsort(-vals, axis=1)  # descending, deterministic
        idx = np.take_along_axis(idx, order, axis=1)
        vals = np.take_along_axis(vals, order, axis=1)
        idt = (np.uint16 if arr.shape[1] - 1 <= np.iinfo(np.uint16).max
               else np.int32)
        out[name + ".idx"] = idx.astype(idt)
        out[name + ".val"] = vals.astype(vdt)
        compressed[name] = {"topk": k, "classes": int(arr.shape[1]),
                            "values": vdt.str}
    return ({"compressed": compressed} if compressed else {}), out


# Non-top-k logit mass is impossible after expansion; this stands in for
# -inf so softmax puts ~zero weight there without inf-arithmetic edges.
EXPAND_FILL = -1e30


def expand_outputs(meta: dict, tensors: dict[str, np.ndarray]
                   ) -> dict[str, np.ndarray]:
    """Scatter-expand a compressed response back to dense fp32 logits
    (non-top-k entries get EXPAND_FILL), leaving downstream losses
    unchanged. Inverse of `compress_outputs`; any rank — the classes
    axis is the LAST one (sequence teachers serve (rows, seq, K))."""
    for name, info in (meta.get("compressed") or {}).items():
        idx = tensors.pop(name + ".idx")
        val = tensors.pop(name + ".val")
        dense = np.full(idx.shape[:-1] + (int(info["classes"]),),
                        EXPAND_FILL, np.float32)
        np.put_along_axis(dense, idx.astype(np.int64),
                          val.astype(np.float32), axis=-1)
        tensors[name] = dense
    return tensors


def _predict_response(out: dict[str, np.ndarray], comp: dict | None,
                      server_meta: dict | None):
    """Build a predict response: client-negotiated compression + the
    server-side sparse announcements. Runs on the per-connection WRITER
    thread, overlapped with the batcher's device stages."""
    compressed = {}
    if comp:  # client-negotiated host-side top-k of dense outs
        # never re-compress outputs the predict_fn already emits
        # sparse (name.idx/name.val) — a smaller client K would
        # otherwise shred name.val into name.val.idx/...
        sparse = {k: v for k, v in out.items()
                  if k.endswith((".idx", ".val"))}
        frag, out = compress_outputs(
            {k: v for k, v in out.items() if k not in sparse}, comp)
        out.update(sparse)
        compressed.update(frag.get("compressed", {}))
    if server_meta:  # predict_fn emitted device-side sparse outs
        compressed.update(
            {name: info for name, info in server_meta.items()
             if name + ".idx" in out})
    if compressed:
        return {"ok": True, "compressed": compressed}, out
    return {"ok": True}, out


class _Handler(socketserver.BaseRequestHandler):
    """Pipelined connection handler: the recv loop submits predict
    requests to the batcher WITHOUT waiting for results; a per-connection
    writer thread completes them strictly in request order (encode +
    compress off the recv path). A client may therefore keep many
    requests in flight on one connection — responses come back FIFO,
    tagged with the request's ``seq`` when it carried one.

    Backpressure: at most MAX_INFLIGHT responses are queued per
    connection; past that the recv loop blocks, which stops reading the
    socket and lets TCP flow control push back on the client.
    """

    MAX_INFLIGHT = 128

    def handle(self) -> None:
        batcher: Batcher = self.server.batcher  # type: ignore[attr-defined]
        server_meta: dict = getattr(self.server, "compressed_meta", {})
        sock: socket.socket = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Register with the server so stop() can hard-close live
        # connections: a stopping teacher must look to its clients like a
        # killed one (immediate RST -> requeue elsewhere), not a silent
        # peer that strands their in-flight requests until rpc_timeout.
        conns = getattr(self.server, "active_conns", None)
        if conns is not None:
            with self.server.conns_lock:  # type: ignore[attr-defined]
                conns.add(sock)
        resp_q: queue.Queue = queue.Queue(maxsize=self.MAX_INFLIGHT)
        writer = threading.Thread(
            target=self._write_loop, args=(sock, resp_q, server_meta),
            daemon=True, name="teacher-conn-send")
        writer.start()
        try:
            while True:
                try:
                    meta, tensors = tensor_wire.recv_tensors(sock)
                except (tensor_wire.TensorWireError, OSError):
                    return
                seq = meta.get("seq")
                # the client's trace context rides meta["_tc"] (tensor
                # wire attaches it); pop it even when tracing is off
                # here so it never leaks into request handling
                remote_ctx = trace.extract(meta)
                if meta.get("op") == "predict":
                    if not tensors:
                        resp_q.put(("done", seq,
                                    {"ok": False,
                                     "error": "no feed tensors"}, {}))
                        continue
                    tenant = meta.get("tenant", "default")
                    prio = meta.get("priority", "normal")
                    # the admission decision is the multi-tenant
                    # attribution point: every accept/shed carries
                    # (tenant, class) so a merged trace answers "whose
                    # requests were shed during THAT pool resize"
                    adm = trace.start_span(
                        "serve.admit", parent=remote_ctx,
                        attrs={"tenant": tenant, "class": prio})
                    try:
                        req = batcher.submit(
                            tensors, tenant=tenant, priority=prio)
                    except AdmissionReject as rej:
                        if adm is not None:
                            adm.end(admitted=False, reason=rej.reason)
                        # typed load-shed response on the SAME open
                        # connection — never a dropped socket: the
                        # client backs off retry_after_ms and retries
                        # (here or on another teacher)
                        resp_q.put(("done", seq,
                                    {"ok": False, "rejected": True,
                                     "error": str(rej),
                                     "reason": rej.reason,
                                     "retry_after_ms": rej.retry_after_ms},
                                    {}))
                        continue
                    if adm is not None:
                        adm.end(admitted=True, rows=req.rows)
                    resp_q.put(("predict", seq, meta.get("compress"), req))
                else:
                    try:
                        resp_meta, resp_tensors = self._control(
                            batcher, meta)
                    except Exception as exc:
                        resp_meta = {"ok": False,
                                     "error": f"{type(exc).__name__}: {exc}"}
                        resp_tensors = {}
                    resp_q.put(("done", seq, resp_meta, resp_tensors))
        finally:
            if conns is not None:
                with self.server.conns_lock:  # type: ignore[attr-defined]
                    conns.discard(sock)
            resp_q.put(None)

    @staticmethod
    def _control(batcher: Batcher, meta: dict):
        op = meta.get("op")
        if op == "ping":
            return {"ok": True}, {}
        if op == "stats":
            return {"ok": True, **batcher.stats()}, {}
        if op == "drain":
            # graceful-shutdown handshake: stop admitting, finish
            # in-flight work; the drain poller watches queue_depth +
            # inflight_groups go quiet before stopping the process
            batcher.begin_drain()
            return {"ok": True, "draining": True}, {}
        return {"ok": False, "error": f"unknown op {op!r}"}, {}

    @staticmethod
    def _write_loop(sock: socket.socket, resp_q: queue.Queue,
                    server_meta: dict) -> None:
        broken = False   # after a send failure keep DRAINING (the recv
        # loop's final sentinel put must never block on a full queue)
        while True:
            item = resp_q.get()
            if item is None:
                return
            if broken:
                continue
            kind, seq, a, b = item
            if kind == "predict":
                req: _Request = b
                req.done.wait()
                if req.error is not None:
                    resp_meta, out = {"ok": False, "error": req.error}, {}
                else:
                    try:
                        resp_meta, out = _predict_response(
                            req.result, a, server_meta)
                    except Exception as exc:
                        resp_meta = {"ok": False,
                                     "error": f"{type(exc).__name__}: {exc}"}
                        out = {}
            else:
                resp_meta, out = a, b
            if seq is not None:
                resp_meta = {**resp_meta, "seq": seq}
            try:
                tensor_wire.send_tensors(sock, resp_meta, out)
            except OSError:
                broken = True


class _ThreadingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TeacherServer:
    """In-process handle: serve `predict_fn` on a TCP port.

    predict_fn: dict[str, np.ndarray] -> dict[str, np.ndarray]; typically a
    jitted model apply. Called only from the batcher thread, with batch
    sizes drawn from `buckets` — so jit compiles once per bucket.
    """

    def __init__(self, predict_fn, *, port: int = 0, host: str = "0.0.0.0",
                 max_batch: int = 64, max_wait: float = 0.002,
                 buckets: tuple[int, ...] = DEFAULT_BUCKETS,
                 compressed_meta: dict[str, dict] | None = None,
                 max_wait_cap: float | None = None,
                 batching: str | None = None,
                 admission: AdmissionConfig | None = None):
        """``compressed_meta``: announce that `predict_fn` ALREADY emits
        sparse ``name.idx``/``name.val`` outputs (device-side
        ``lax.top_k`` — only K values ever cross host<->device instead
        of the full class row). Shape: ``{name: {"topk": K, "classes":
        C, "values": "<f2"}}``; it is attached to predict responses so
        dense clients scatter-expand transparently while sparse clients
        consume as-is."""
        self.batcher = Batcher(predict_fn, max_batch=max_batch,
                               max_wait=max_wait, buckets=buckets,
                               max_wait_cap=max_wait_cap,
                               batching=batching, admission=admission)
        self.compressed_meta = dict(compressed_meta or {})
        self._server = _ThreadingServer((host, port), _Handler)
        self._server.batcher = self.batcher  # type: ignore[attr-defined]
        self._server.compressed_meta = self.compressed_meta  # type: ignore[attr-defined]
        self._server.active_conns = set()  # type: ignore[attr-defined]
        self._server.conns_lock = threading.Lock()  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._started = False
        # the Batcher's stats() dict stays the registrar's API; the
        # per-process obs registry serves the same numbers as gauges
        self._obs = obs_metrics.register_stats("teacher",
                                               self.batcher.stats)

    def start(self) -> "TeacherServer":
        if self._started:
            return self
        self._started = True
        self.batcher.start()
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="teacher-serve").start()
        log.info("teacher server on :%d", self.port)
        return self

    def drain(self) -> None:
        """Stop admitting new requests; in-flight work completes. The
        in-process mirror of the wire ``op: "drain"``."""
        self.batcher.begin_drain()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # Hard-close live connections: clients see ECONNRESET now and
        # requeue their in-flight work to surviving teachers at once,
        # exactly as if the process had been killed — without this they
        # stall head-of-line until rpc_timeout (measured as a 60s e2e
        # dip in bench_distill_churn before the fix).
        with self._server.conns_lock:  # type: ignore[attr-defined]
            conns = list(self._server.active_conns)  # type: ignore[attr-defined]
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self.batcher.stop()
        obs_metrics.unregister(self._obs)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class TeacherRejected(tensor_wire.TensorWireError):
    """Typed admission rejection off the wire: the teacher answered
    ``{"ok": false, "rejected": true, "retry_after_ms": R}`` instead of
    serving. NOT a dead connection — the socket stays usable; callers
    back off ``retry_after_s`` (jittered) and retry, here or on another
    teacher (reader.py's bounded shed-retry budget)."""

    def __init__(self, message: str, retry_after_ms: float = 100.0,
                 reason: str = "overload"):
        super().__init__(message)
        self.retry_after_ms = float(retry_after_ms)
        self.reason = reason

    @property
    def retry_after_s(self) -> float:
        return self.retry_after_ms / 1e3


class _PendingPredict:
    """Handle for one in-flight request on a pipelined TeacherClient.
    ``result()`` blocks until THIS request's response arrives (receiving
    and completing any earlier in-flight requests along the way — the
    server responds strictly in request order per connection)."""

    __slots__ = ("_client", "seq", "_meta", "_tensors", "_arrived")

    def __init__(self, client: "TeacherClient", seq: int):
        self._client = client
        self.seq = seq
        self._meta: dict | None = None
        self._tensors: dict | None = None
        self._arrived = False

    def response(self) -> tuple[dict, dict]:
        """Raw (meta, tensors) of the response, no ok-check/expansion."""
        self._client._wait_for(self)
        return self._meta, self._tensors  # type: ignore[return-value]

    def result(self) -> dict[str, np.ndarray]:
        """Predict semantics: raise on server error, expand per the
        client's negotiation settings."""
        meta, tensors = self.response()
        if not meta.get("ok"):
            if meta.get("rejected"):
                raise TeacherRejected(
                    meta.get("error", "admission rejected"),
                    meta.get("retry_after_ms", 100.0),
                    meta.get("reason", "overload"))
            raise tensor_wire.TensorWireError(
                meta.get("error", "predict failed"))
        if self._client.expand:
            tensors = expand_outputs(meta, tensors)
        return tensors


class TeacherClient:
    """Client of one teacher server (used by DistillReader's predict
    workers; the reference counterpart wraps paddle_serving_client,
    distill_worker.py:187-282).

    ``predict`` is the blocking one-shot; ``predict_async`` returns a
    `_PendingPredict` handle and may be called again before resolving it,
    keeping up to ``max_inflight`` requests pipelined on the ONE
    connection — the r6 lever that hides teacher round-trip latency under
    student compute. Requests are sequence-tagged and the server echoes
    the tag; a FIFO mismatch fails loudly instead of silently pairing a
    response with the wrong request. Not thread-safe by design: each
    reader worker owns its client (a lock still guards the send path for
    accidental sharing).

    ``compress_topk > 0`` negotiates top-k+fp16 logit compression per
    request (see `compress_outputs`); with ``expand=True`` (default) the
    response is scatter-expanded back to dense fp32 transparently, with
    ``expand=False`` the sparse ``name.idx``/``name.val`` pair is
    returned for sparse-aware losses (train/classification.py
    `make_sparse_distill_step`)."""

    def __init__(self, endpoint: str, timeout: float = 30.0, *,
                 compress_topk: int = 0, compress_values: str = "float16",
                 expand: bool = True, max_inflight: int = 32,
                 tenant: str = "", priority: str = ""):
        from edl_tpu.utils.net import split_endpoint
        self.endpoint = endpoint
        self.compress_topk = int(compress_topk)
        self.compress_values = compress_values
        self.expand = expand
        # multi-tenant identity: attached to every predict request so
        # the teacher's admission plane can queue/shed per (tenant,
        # priority class). Empty = the server's defaults.
        self.tenant = tenant
        self.priority = priority
        self.max_inflight = max(1, int(max_inflight))
        host, port = split_endpoint(endpoint)
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._seq = 0
        self._pending: "deque[_PendingPredict]" = deque()
        self._send_lock = threading.Lock()

    def _submit(self, meta: dict, tensors: dict | None = None
                ) -> _PendingPredict:
        with self._send_lock:
            if len(self._pending) >= self.max_inflight:
                self._recv_one()   # bound memory: drain the oldest
            handle = _PendingPredict(self, self._seq)
            self._seq += 1
            tensor_wire.send_tensors(self._sock,
                                     {**meta, "seq": handle.seq}, tensors)
            self._pending.append(handle)
        return handle

    def _recv_one(self) -> None:
        meta, tensors = tensor_wire.recv_tensors(self._sock)
        if not self._pending:
            raise tensor_wire.TensorWireError(
                "response with no request in flight")
        h = self._pending.popleft()
        rseq = meta.get("seq")
        if rseq is not None and rseq != h.seq:
            raise tensor_wire.TensorWireError(
                f"pipelining desync: response seq {rseq} != expected "
                f"{h.seq} on {self.endpoint}")
        h._meta, h._tensors, h._arrived = meta, tensors, True

    def _wait_for(self, handle: _PendingPredict) -> None:
        while not handle._arrived:
            self._recv_one()

    def inflight(self) -> int:
        return len(self._pending)

    def predict_async(self, feeds: dict[str, np.ndarray]) -> _PendingPredict:
        meta: dict = {"op": "predict"}
        if self.compress_topk > 0:
            meta["compress"] = {"topk": self.compress_topk,
                                "values": self.compress_values}
        if self.tenant:
            meta["tenant"] = self.tenant
        if self.priority:
            meta["priority"] = self.priority
        return self._submit(meta, feeds)

    def predict(self, feeds: dict[str, np.ndarray]
                ) -> dict[str, np.ndarray]:
        return self.predict_async(feeds).result()

    def ping(self) -> bool:
        try:
            meta, _ = self._submit({"op": "ping"}).response()
            return bool(meta.get("ok"))
        except (tensor_wire.TensorWireError, OSError):
            return False

    def drain(self) -> bool:
        """Ask the remote teacher to stop admitting (op: drain)."""
        try:
            meta, _ = self._submit({"op": "drain"}).response()
            return bool(meta.get("ok"))
        except (tensor_wire.TensorWireError, OSError):
            return False

    def stats(self) -> dict:
        """Serving counters of the remote teacher (op: stats)."""
        meta, _ = self._submit({"op": "stats"}).response()
        if not meta.get("ok"):
            raise tensor_wire.TensorWireError(
                meta.get("error", "stats failed"))
        return {k: v for k, v in meta.items() if k not in ("ok", "seq")}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _build_model_predict(model_name: str, num_classes: int, params_path: str,
                         input_key: str, output_key: str,
                         input_shape: tuple[int, ...] = (32, 32, 3),
                         input_dtype: str = "float32",
                         serve_topk: int = 0,
                         local_mesh: str = "",
                         input_normalize: str = ""):
    """CLI helper: jitted zoo-model forward with random or restored
    params; returns ``(predict, compressed_meta)`` (meta None without
    serve_topk). ``serve_topk > 0``: `lax.top_k` runs ON DEVICE and only
    (idx, val) pairs cross to host — at 1000 classes and K=16 that is a
    62x smaller device->host pull per row, usually the serving
    bottleneck after the feeds themselves."""
    import jax
    import jax.numpy as jnp

    from edl_tpu import models as zoo
    from edl_tpu.train.classification import create_state
    import optax

    factory = zoo.get_model(model_name)
    model = factory(num_classes=num_classes)
    if serve_topk > num_classes:
        # lax.top_k rejects k > axis size — clamp instead of crashing
        # the first predict (a 1000-class default K on a small head)
        log.warning("--serve-topk %d > %d classes; clamping", serve_topk,
                    num_classes)
        serve_topk = num_classes
    # Dense layers bind their kernel to the flattened input size, so init
    # must see the shape that will be served.
    state = create_state(model, jax.random.PRNGKey(0), (1,) + input_shape,
                         optax.identity(),
                         input_dtype=jnp.dtype(input_dtype))
    if params_path:
        from edl_tpu.train.checkpoint import CheckpointManager
        from edl_tpu.utils.fs import split_scheme
        # gs://... / hdfs://... params mirrors download before restore
        # (reference download_hdfs_file, distill/utils.py:18)
        scheme, rest = split_scheme(params_path)
        if scheme not in ("", "file"):
            import tempfile
            local = tempfile.mkdtemp(prefix="edl-teacher-params-")
            mgr = CheckpointManager(local, remote=params_path)
        else:
            mgr = CheckpointManager(rest if scheme == "file" else params_path)
        try:
            # Structure-free: the trainer's checkpoint carries ITS
            # optimizer state (momentum/wd chains) which the serving
            # process neither has nor wants — take the model sub-trees.
            restored = mgr.restore_raw()
            if restored is not None:
                raw = restored[0]
                state = state.replace(params=raw["params"],
                                      batch_stats=raw.get("batch_stats")
                                      or state.batch_stats)
                log.info("teacher params restored from %s (epoch=%d)",
                         params_path, restored[1].epoch)
        finally:
            mgr.close(raise_errors=False)

    variables = {"params": state.params}
    if state.batch_stats is not None:
        variables["batch_stats"] = state.batch_stats

    # On-device pixel normalization matching what the model was TRAINED
    # with: distill students on the JPEG plane ship raw uint8 feeds, so
    # a teacher trained on normalized inputs must normalize server-side
    # or its logits are out-of-distribution garbage.
    from edl_tpu.train.classification import normalize_image
    norm = input_normalize or None
    base_apply = model.apply

    def apply_with_norm(v, x, **kw):
        return base_apply(v, normalize_image(x, norm), **kw)

    if local_mesh:
        # One process drives all local chips: dp-sharded batch over a
        # local mesh, replicated params (zoo CNNs carry no tp
        # annotations; transformer-family teachers use the library API —
        # distill/sharded_teacher.py — with tp-sharded variables).
        from edl_tpu.distill.sharded_teacher import (parse_local_mesh,
                                                     sharded_predict_fn)
        from edl_tpu.parallel import mesh as mesh_lib
        mesh = parse_local_mesh(local_mesh)
        placed = mesh_lib.replicate_host_tree(mesh,
                                              jax.device_get(variables))
        return sharded_predict_fn(
            lambda v, x: apply_with_norm(v, x, train=False), placed, mesh,
            input_key=input_key, output_key=output_key,
            batch_axes=("dp",), input_dtype=jnp.dtype(input_dtype),
            serve_topk=serve_topk, classes=num_classes)

    @jax.jit
    def forward(images):
        logits = apply_with_norm(variables, images, train=False)
        if serve_topk:
            from jax import lax
            val, idx = lax.top_k(logits.astype(jnp.float32), serve_topk)
            # wire dtypes ON DEVICE: the batcher's complete stage only
            # fetches, never converts
            return idx.astype(jnp.int32), val.astype(jnp.float16)
        return logits.astype(jnp.float32)

    # device arrays are returned UNFETCHED: jit dispatch is async, so the
    # batcher's compute thread immediately feeds the chip the next
    # coalesced batch while the complete stage pulls these to host.
    if serve_topk:
        def predict(feeds):
            feed = jnp.asarray(feeds[input_key]).astype(
                jnp.dtype(input_dtype))
            idx, val = forward(feed)
            return {output_key + ".idx": idx, output_key + ".val": val}
    else:
        def predict(feeds):
            feed = jnp.asarray(feeds[input_key]).astype(
                jnp.dtype(input_dtype))
            return {output_key: forward(feed)}

    meta = None
    if serve_topk:
        meta = {output_key: {"topk": serve_topk,
                             "classes": num_classes, "values": "<f2"}}
    return predict, meta


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.distill.teacher_server",
        description="Serve a zoo model as a distill teacher")
    parser.add_argument("--model", default="mlp",
                        help="edl_tpu.models factory name (mlp, resnet50_vd, ...)")
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--params", default="",
                        help="checkpoint dir (or gs:///hdfs:// mirror URI) "
                             "to restore params from")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=23900)
    parser.add_argument("--input-key", default="image")
    parser.add_argument("--output-key", default="logits")
    parser.add_argument("--input-shape", default="32,32,3",
                        help="per-sample input shape, e.g. 28,28,1")
    parser.add_argument("--input-dtype", default="float32",
                        help="float32 for images, int32 for token ids")
    parser.add_argument("--input-normalize", default="",
                        choices=("", "imagenet", "unit"),
                        help="on-device pixel normalization of feeds "
                             "(MUST match the teacher's training "
                             "preprocessing when students ship raw "
                             "uint8, e.g. the JPEG plane)")
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--max-wait-ms", type=float, default=2.0)
    parser.add_argument("--batching", default="",
                        choices=("", "continuous", "window"),
                        help="batch admission mode (default: "
                             "EDL_TPU_SERVE_BATCHING or continuous)")
    parser.add_argument("--serve-topk", type=int, default=0,
                        help="device-side top-k: serve only K "
                             "(idx, fp16 val) pairs per row instead of "
                             "the dense class row")
    parser.add_argument("--local-mesh", default="",
                        help="drive ALL local chips from this one "
                             "process, e.g. 'dp=8' (sharded_teacher.py)")
    args = parser.parse_args(argv)
    shape = tuple(int(x) for x in args.input_shape.split(","))
    predict, compressed_meta = _build_model_predict(
        args.model, args.num_classes, args.params,
        args.input_key, args.output_key, shape,
        args.input_dtype, args.serve_topk, args.local_mesh,
        args.input_normalize)
    server = TeacherServer(predict, port=args.port, host=args.host,
                           max_batch=args.max_batch,
                           max_wait=args.max_wait_ms / 1000.0,
                           compressed_meta=compressed_meta,
                           batching=args.batching or None)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
