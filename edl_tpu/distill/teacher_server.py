"""JAX teacher inference server: batched forward serving over tensor wire.

The TPU-native stand-in for the reference's Paddle Serving teacher
(README.md:74-92; students call it through distill_worker.py:203-226). One
process drives the local TPU chips; a coalescing batcher concatenates
concurrent client requests into one device batch and pads to a fixed
bucket so XLA compiles once per bucket (static shapes — no recompiles on
ragged tails). This coalescing is what Paddle Serving gave the reference
for free and SURVEY.md §7 flags as a hard part of hitting ≥1500 img/s.

Protocol (tensor_wire frames):
    request  meta {"op": "predict"}          tensors {feed_name: array}
    response meta {"ok": true}               tensors {fetch_name: array}
    request  meta {"op": "ping"}             -> {"ok": true}, no tensors

Wire compression (two independent levers; see `compress_outputs`):
  - client-negotiated: request meta carries {"compress": {"topk": K,
    "values": "float16"}} and eligible dense outputs come back as
    name.idx/name.val with meta {"compressed": {name: {...}}};
  - server-side device top-k: predict_fn emits name.idx/name.val
    directly (lax.top_k before the host transfer, CLI --serve-topk);
    the server announces the same meta from `compressed_meta`.
  Dense clients scatter-expand transparently (`expand_outputs`); sparse
  clients (TeacherClient(expand=False)) consume idx/val as-is with
  train/classification.py `make_sparse_distill_step`. Feeds travel in
  the caller's dtype — send uint8 images and normalize teacher-side for
  a 4x cheaper request direction.

CLI (serves a zoo model with random or checkpointed params):
    python -m edl_tpu.distill.teacher_server --model mlp --port 23900
"""

from __future__ import annotations

import argparse
import queue
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from edl_tpu.distill import tensor_wire
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.teacher_server")


DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def pad_to_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n  # beyond the largest bucket: serve exact (rare, recompiles)


@dataclass
class _Request:
    tensors: dict[str, np.ndarray]
    rows: int
    done: threading.Event = field(default_factory=threading.Event)
    result: dict[str, np.ndarray] | None = None
    error: str | None = None


class Batcher:
    """Coalesce concurrent predict requests into padded device batches."""

    def __init__(self, predict_fn, *, max_batch: int = 64,
                 max_wait: float = 0.002,
                 buckets: tuple[int, ...] = DEFAULT_BUCKETS):
        self.predict_fn = predict_fn
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.buckets = tuple(sorted(buckets))
        self._q: queue.Queue[_Request | None] = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="teacher-batcher")
        # Cumulative utilization counters (the registry `info` data source:
        # reference discovery/register.py:36-40 reserves the field for
        # "report job performance to the scheduler").
        self._stats_lock = threading.Lock()
        self._served_rows = 0
        self._served_requests = 0
        self._busy_s = 0.0
        self._started_at = time.monotonic()
        # Coalescing histogram: device-batch ROW count (pre-padding) ->
        # number of served groups. Whether concurrent client requests
        # actually merge (vs degenerate 1-request batches) is THE
        # efficiency question for a serving pool; the histogram makes it
        # observable instead of inferred.
        self._batch_hist: dict[int, int] = {}

    def start(self) -> "Batcher":
        self._thread.start()
        return self

    def submit(self, tensors: dict[str, np.ndarray]) -> _Request:
        rows = next(iter(tensors.values())).shape[0] if tensors else 0
        req = _Request(tensors=tensors, rows=rows)
        self._q.put(req)
        return req

    def _collect(self) -> list[_Request]:
        """One blocking pop, then drain whatever arrives within max_wait
        (bounded by max_batch rows)."""
        try:
            first = self._q.get(timeout=0.2)
        except queue.Empty:
            return []
        if first is None:
            return []
        group, rows = [first], first.rows
        deadline = time.monotonic() + self.max_wait
        while rows < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if req is None:
                break
            if rows + req.rows > self.max_batch:
                # Doesn't fit this round: run it in the next group.
                self._q.put(req)
                break
            group.append(req)
            rows += req.rows
        return group

    def _run(self) -> None:
        while not self._stop.is_set():
            group = self._collect()
            if not group:
                continue
            try:
                self._serve_group(group)
            except Exception as exc:
                log.exception("batch predict failed")
                for req in group:
                    if req.done.is_set():
                        # Heterogeneous requests already served (recursively)
                        # by _serve_group must not be retroactively failed.
                        continue
                    req.error = f"{type(exc).__name__}: {exc}"
                    req.done.set()

    def _serve_group(self, group: list[_Request]) -> None:
        names = list(group[0].tensors)
        for req in group[1:]:
            if list(req.tensors) != names:
                # Heterogeneous feeds can't coalesce; serve separately.
                self._serve_group([req])
        group = [g for g in group if list(g.tensors) == names]
        rows = sum(g.rows for g in group)
        bucket = pad_to_bucket(rows, self.buckets)
        feeds = {}
        for name in names:
            cat = np.concatenate([g.tensors[name] for g in group], axis=0)
            if bucket > rows:
                pad = np.zeros((bucket - rows,) + cat.shape[1:], cat.dtype)
                cat = np.concatenate([cat, pad], axis=0)
            feeds[name] = cat
        t0 = time.monotonic()
        outs = self.predict_fn(feeds)
        outs = {k: np.asarray(v) for k, v in outs.items()}
        with self._stats_lock:
            self._busy_s += time.monotonic() - t0
            self._served_rows += rows
            self._served_requests += len(group)
            self._batch_hist[rows] = self._batch_hist.get(rows, 0) + 1
        offset = 0
        for req in group:
            req.result = {k: v[offset:offset + req.rows]
                          for k, v in outs.items()}
            offset += req.rows
            req.done.set()

    def stats(self) -> dict:
        """Cumulative serving counters (consumed by TeacherRegistrar)."""
        with self._stats_lock:
            hist = dict(sorted(self._batch_hist.items()))
            groups = sum(hist.values())
            rows_mean = (sum(r * c for r, c in hist.items()) / groups
                         if groups else 0.0)
            return {"served_rows": self._served_rows,
                    "served_requests": self._served_requests,
                    "busy_s": round(self._busy_s, 4),
                    "uptime_s": round(time.monotonic() - self._started_at, 4),
                    "queue_depth": self._q.qsize(),
                    # JSON object keys are strings on the wire
                    "batch_rows_hist": {str(r): c for r, c in hist.items()},
                    "batch_rows_mean": round(rows_mean, 2)}

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=5.0)


def compress_outputs(outs: dict[str, np.ndarray], spec: dict
                     ) -> tuple[dict, dict[str, np.ndarray]]:
    """Top-k + narrow-dtype compression of eligible prediction tensors.

    ``spec`` = ``{"topk": K, "values": "float16"}`` (client-negotiated
    per request). A 2-D floating (rows, classes) tensor with classes > K
    becomes ``name.idx`` (uint16 when classes fit, else int32; sorted by
    descending value) + ``name.val`` (K values in the narrow dtype);
    everything else passes through unchanged. Returns a meta fragment
    ``{"compressed": {name: {topk, classes, values}}}`` the client uses
    to expand — at 1000 classes and K=8 this turns 4000 B/row of fp32
    logits into 32 B/row, the lever the reference got from Paddle
    Serving's fetch-var selection (distill_worker.py:203-226).
    """
    k = int(spec.get("topk", 0))
    vdt = np.dtype(spec.get("values", "float16"))
    compressed: dict[str, dict] = {}
    out: dict[str, np.ndarray] = {}
    for name, arr in outs.items():
        if not (k > 0 and arr.ndim == 2 and arr.shape[1] > k
                and np.issubdtype(arr.dtype, np.floating)):
            out[name] = arr
            continue
        idx = np.argpartition(arr, -k, axis=1)[:, -k:]
        vals = np.take_along_axis(arr, idx, axis=1)
        order = np.argsort(-vals, axis=1)  # descending, deterministic
        idx = np.take_along_axis(idx, order, axis=1)
        vals = np.take_along_axis(vals, order, axis=1)
        idt = (np.uint16 if arr.shape[1] - 1 <= np.iinfo(np.uint16).max
               else np.int32)
        out[name + ".idx"] = idx.astype(idt)
        out[name + ".val"] = vals.astype(vdt)
        compressed[name] = {"topk": k, "classes": int(arr.shape[1]),
                            "values": vdt.str}
    return ({"compressed": compressed} if compressed else {}), out


# Non-top-k logit mass is impossible after expansion; this stands in for
# -inf so softmax puts ~zero weight there without inf-arithmetic edges.
EXPAND_FILL = -1e30


def expand_outputs(meta: dict, tensors: dict[str, np.ndarray]
                   ) -> dict[str, np.ndarray]:
    """Scatter-expand a compressed response back to dense fp32 logits
    (non-top-k entries get EXPAND_FILL), leaving downstream losses
    unchanged. Inverse of `compress_outputs`; any rank — the classes
    axis is the LAST one (sequence teachers serve (rows, seq, K))."""
    for name, info in (meta.get("compressed") or {}).items():
        idx = tensors.pop(name + ".idx")
        val = tensors.pop(name + ".val")
        dense = np.full(idx.shape[:-1] + (int(info["classes"]),),
                        EXPAND_FILL, np.float32)
        np.put_along_axis(dense, idx.astype(np.int64),
                          val.astype(np.float32), axis=-1)
        tensors[name] = dense
    return tensors


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        batcher: Batcher = self.server.batcher  # type: ignore[attr-defined]
        server_meta: dict = getattr(self.server, "compressed_meta", {})
        sock: socket.socket = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                meta, tensors = tensor_wire.recv_tensors(sock)
            except (tensor_wire.TensorWireError, OSError):
                return
            try:
                resp_meta, resp_tensors = self._dispatch(
                    batcher, meta, tensors, server_meta)
            except Exception as exc:
                resp_meta = {"ok": False,
                             "error": f"{type(exc).__name__}: {exc}"}
                resp_tensors = {}
            try:
                tensor_wire.send_tensors(sock, resp_meta, resp_tensors)
            except OSError:
                return

    @staticmethod
    def _dispatch(batcher: Batcher, meta: dict, tensors: dict,
                  server_meta: dict | None = None):
        op = meta.get("op")
        if op == "ping":
            return {"ok": True}, {}
        if op == "stats":
            return {"ok": True, **batcher.stats()}, {}
        if op == "predict":
            if not tensors:
                return {"ok": False, "error": "no feed tensors"}, {}
            req = batcher.submit(tensors)
            req.done.wait()
            if req.error is not None:
                return {"ok": False, "error": req.error}, {}
            out = req.result
            compressed = {}
            comp = meta.get("compress")
            if comp:  # client-negotiated host-side top-k of dense outs
                # never re-compress outputs the predict_fn already emits
                # sparse (name.idx/name.val) — a smaller client K would
                # otherwise shred name.val into name.val.idx/...
                sparse = {k: v for k, v in out.items()
                          if k.endswith((".idx", ".val"))}
                frag, out = compress_outputs(
                    {k: v for k, v in out.items() if k not in sparse},
                    comp)
                out.update(sparse)
                compressed.update(frag.get("compressed", {}))
            if server_meta:  # predict_fn emitted device-side sparse outs
                compressed.update(
                    {name: info for name, info in server_meta.items()
                     if name + ".idx" in out})
            if compressed:
                return {"ok": True, "compressed": compressed}, out
            return {"ok": True}, out
        return {"ok": False, "error": f"unknown op {op!r}"}, {}


class _ThreadingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TeacherServer:
    """In-process handle: serve `predict_fn` on a TCP port.

    predict_fn: dict[str, np.ndarray] -> dict[str, np.ndarray]; typically a
    jitted model apply. Called only from the batcher thread, with batch
    sizes drawn from `buckets` — so jit compiles once per bucket.
    """

    def __init__(self, predict_fn, *, port: int = 0, host: str = "0.0.0.0",
                 max_batch: int = 64, max_wait: float = 0.002,
                 buckets: tuple[int, ...] = DEFAULT_BUCKETS,
                 compressed_meta: dict[str, dict] | None = None):
        """``compressed_meta``: announce that `predict_fn` ALREADY emits
        sparse ``name.idx``/``name.val`` outputs (device-side
        ``lax.top_k`` — only K values ever cross host<->device instead
        of the full class row). Shape: ``{name: {"topk": K, "classes":
        C, "values": "<f2"}}``; it is attached to predict responses so
        dense clients scatter-expand transparently while sparse clients
        consume as-is."""
        self.batcher = Batcher(predict_fn, max_batch=max_batch,
                               max_wait=max_wait, buckets=buckets)
        self.compressed_meta = dict(compressed_meta or {})
        self._server = _ThreadingServer((host, port), _Handler)
        self._server.batcher = self.batcher  # type: ignore[attr-defined]
        self._server.compressed_meta = self.compressed_meta  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._started = False

    def start(self) -> "TeacherServer":
        if self._started:
            return self
        self._started = True
        self.batcher.start()
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="teacher-serve").start()
        log.info("teacher server on :%d", self.port)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self.batcher.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class TeacherClient:
    """Blocking client of one teacher server (used by DistillReader's
    predict workers; the reference counterpart wraps paddle_serving_client,
    distill_worker.py:187-282).

    ``compress_topk > 0`` negotiates top-k+fp16 logit compression per
    request (see `compress_outputs`); with ``expand=True`` (default) the
    response is scatter-expanded back to dense fp32 transparently, with
    ``expand=False`` the sparse ``name.idx``/``name.val`` pair is
    returned for sparse-aware losses (train/classification.py
    `make_sparse_distill_step`)."""

    def __init__(self, endpoint: str, timeout: float = 30.0, *,
                 compress_topk: int = 0, compress_values: str = "float16",
                 expand: bool = True):
        from edl_tpu.utils.net import split_endpoint
        self.endpoint = endpoint
        self.compress_topk = int(compress_topk)
        self.compress_values = compress_values
        self.expand = expand
        host, port = split_endpoint(endpoint)
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def predict(self, feeds: dict[str, np.ndarray]
                ) -> dict[str, np.ndarray]:
        meta: dict = {"op": "predict"}
        if self.compress_topk > 0:
            meta["compress"] = {"topk": self.compress_topk,
                                "values": self.compress_values}
        tensor_wire.send_tensors(self._sock, meta, feeds)
        meta, tensors = tensor_wire.recv_tensors(self._sock)
        if not meta.get("ok"):
            raise tensor_wire.TensorWireError(
                meta.get("error", "predict failed"))
        if self.expand:
            tensors = expand_outputs(meta, tensors)
        return tensors

    def ping(self) -> bool:
        try:
            tensor_wire.send_tensors(self._sock, {"op": "ping"})
            meta, _ = tensor_wire.recv_tensors(self._sock)
            return bool(meta.get("ok"))
        except (tensor_wire.TensorWireError, OSError):
            return False

    def stats(self) -> dict:
        """Serving counters of the remote teacher (op: stats)."""
        tensor_wire.send_tensors(self._sock, {"op": "stats"})
        meta, _ = tensor_wire.recv_tensors(self._sock)
        if not meta.get("ok"):
            raise tensor_wire.TensorWireError(
                meta.get("error", "stats failed"))
        return {k: v for k, v in meta.items() if k != "ok"}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _build_model_predict(model_name: str, num_classes: int, params_path: str,
                         input_key: str, output_key: str,
                         input_shape: tuple[int, ...] = (32, 32, 3),
                         input_dtype: str = "float32",
                         serve_topk: int = 0,
                         local_mesh: str = "",
                         input_normalize: str = ""):
    """CLI helper: jitted zoo-model forward with random or restored
    params; returns ``(predict, compressed_meta)`` (meta None without
    serve_topk). ``serve_topk > 0``: `lax.top_k` runs ON DEVICE and only
    (idx, val) pairs cross to host — at 1000 classes and K=16 that is a
    62x smaller device->host pull per row, usually the serving
    bottleneck after the feeds themselves."""
    import jax
    import jax.numpy as jnp

    from edl_tpu import models as zoo
    from edl_tpu.train.classification import create_state
    import optax

    factory = zoo.get_model(model_name)
    model = factory(num_classes=num_classes)
    if serve_topk > num_classes:
        # lax.top_k rejects k > axis size — clamp instead of crashing
        # the first predict (a 1000-class default K on a small head)
        log.warning("--serve-topk %d > %d classes; clamping", serve_topk,
                    num_classes)
        serve_topk = num_classes
    # Dense layers bind their kernel to the flattened input size, so init
    # must see the shape that will be served.
    state = create_state(model, jax.random.PRNGKey(0), (1,) + input_shape,
                         optax.identity(),
                         input_dtype=jnp.dtype(input_dtype))
    if params_path:
        from edl_tpu.train.checkpoint import CheckpointManager
        from edl_tpu.utils.fs import split_scheme
        # gs://... / hdfs://... params mirrors download before restore
        # (reference download_hdfs_file, distill/utils.py:18)
        scheme, rest = split_scheme(params_path)
        if scheme not in ("", "file"):
            import tempfile
            local = tempfile.mkdtemp(prefix="edl-teacher-params-")
            mgr = CheckpointManager(local, remote=params_path)
        else:
            mgr = CheckpointManager(rest if scheme == "file" else params_path)
        # Structure-free: the trainer's checkpoint carries ITS optimizer
        # state (momentum/wd chains) which the serving process neither
        # has nor wants — take only the model sub-trees.
        restored = mgr.restore_raw()
        if restored is not None:
            raw = restored[0]
            state = state.replace(params=raw["params"],
                                  batch_stats=raw.get("batch_stats")
                                  or state.batch_stats)
            log.info("teacher params restored from %s (epoch=%d)",
                     params_path, restored[1].epoch)

    variables = {"params": state.params}
    if state.batch_stats is not None:
        variables["batch_stats"] = state.batch_stats

    # On-device pixel normalization matching what the model was TRAINED
    # with: distill students on the JPEG plane ship raw uint8 feeds, so
    # a teacher trained on normalized inputs must normalize server-side
    # or its logits are out-of-distribution garbage.
    from edl_tpu.train.classification import normalize_image
    norm = input_normalize or None
    base_apply = model.apply

    def apply_with_norm(v, x, **kw):
        return base_apply(v, normalize_image(x, norm), **kw)

    if local_mesh:
        # One process drives all local chips: dp-sharded batch over a
        # local mesh, replicated params (zoo CNNs carry no tp
        # annotations; transformer-family teachers use the library API —
        # distill/sharded_teacher.py — with tp-sharded variables).
        from edl_tpu.distill.sharded_teacher import (parse_local_mesh,
                                                     sharded_predict_fn)
        from edl_tpu.parallel import mesh as mesh_lib
        mesh = parse_local_mesh(local_mesh)
        placed = mesh_lib.replicate_host_tree(mesh,
                                              jax.device_get(variables))
        return sharded_predict_fn(
            lambda v, x: apply_with_norm(v, x, train=False), placed, mesh,
            input_key=input_key, output_key=output_key,
            batch_axes=("dp",), input_dtype=jnp.dtype(input_dtype),
            serve_topk=serve_topk, classes=num_classes)

    @jax.jit
    def forward(images):
        logits = apply_with_norm(variables, images, train=False)
        if serve_topk:
            from jax import lax
            val, idx = lax.top_k(logits.astype(jnp.float32), serve_topk)
            return idx.astype(jnp.int32), val
        return logits

    if serve_topk:
        def predict(feeds):
            feed = jnp.asarray(feeds[input_key]).astype(
                jnp.dtype(input_dtype))
            idx, val = forward(feed)
            return {output_key + ".idx": np.asarray(idx, np.int32),
                    output_key + ".val":
                        np.asarray(val).astype(np.float16)}
    else:
        def predict(feeds):
            feed = jnp.asarray(feeds[input_key]).astype(
                jnp.dtype(input_dtype))
            return {output_key: np.asarray(forward(feed), np.float32)}

    meta = None
    if serve_topk:
        meta = {output_key: {"topk": serve_topk,
                             "classes": num_classes, "values": "<f2"}}
    return predict, meta


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.distill.teacher_server",
        description="Serve a zoo model as a distill teacher")
    parser.add_argument("--model", default="mlp",
                        help="edl_tpu.models factory name (mlp, resnet50_vd, ...)")
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--params", default="",
                        help="checkpoint dir (or gs:///hdfs:// mirror URI) "
                             "to restore params from")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=23900)
    parser.add_argument("--input-key", default="image")
    parser.add_argument("--output-key", default="logits")
    parser.add_argument("--input-shape", default="32,32,3",
                        help="per-sample input shape, e.g. 28,28,1")
    parser.add_argument("--input-dtype", default="float32",
                        help="float32 for images, int32 for token ids")
    parser.add_argument("--input-normalize", default="",
                        choices=("", "imagenet", "unit"),
                        help="on-device pixel normalization of feeds "
                             "(MUST match the teacher's training "
                             "preprocessing when students ship raw "
                             "uint8, e.g. the JPEG plane)")
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--max-wait-ms", type=float, default=2.0)
    parser.add_argument("--serve-topk", type=int, default=0,
                        help="device-side top-k: serve only K "
                             "(idx, fp16 val) pairs per row instead of "
                             "the dense class row")
    parser.add_argument("--local-mesh", default="",
                        help="drive ALL local chips from this one "
                             "process, e.g. 'dp=8' (sharded_teacher.py)")
    args = parser.parse_args(argv)
    shape = tuple(int(x) for x in args.input_shape.split(","))
    predict, compressed_meta = _build_model_predict(
        args.model, args.num_classes, args.params,
        args.input_key, args.output_key, shape,
        args.input_dtype, args.serve_topk, args.local_mesh,
        args.input_normalize)
    server = TeacherServer(predict, port=args.port, host=args.host,
                           max_batch=args.max_batch,
                           max_wait=args.max_wait_ms / 1000.0,
                           compressed_meta=compressed_meta)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
