"""JAX teacher inference server: batched forward serving over tensor wire.

The TPU-native stand-in for the reference's Paddle Serving teacher
(README.md:74-92; students call it through distill_worker.py:203-226). One
process drives the local TPU chips; a coalescing batcher concatenates
concurrent client requests into one device batch and pads to a fixed
bucket so XLA compiles once per bucket (static shapes — no recompiles on
ragged tails). This coalescing is what Paddle Serving gave the reference
for free and SURVEY.md §7 flags as a hard part of hitting ≥1500 img/s.

Protocol (tensor_wire frames):
    request  meta {"op": "predict"}          tensors {feed_name: array}
    response meta {"ok": true}               tensors {fetch_name: array}
    request  meta {"op": "ping"}             -> {"ok": true}, no tensors

CLI (serves a zoo model with random or checkpointed params):
    python -m edl_tpu.distill.teacher_server --model mlp --port 23900
"""

from __future__ import annotations

import argparse
import queue
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from edl_tpu.distill import tensor_wire
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.distill.teacher_server")


DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def pad_to_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n  # beyond the largest bucket: serve exact (rare, recompiles)


@dataclass
class _Request:
    tensors: dict[str, np.ndarray]
    rows: int
    done: threading.Event = field(default_factory=threading.Event)
    result: dict[str, np.ndarray] | None = None
    error: str | None = None


class Batcher:
    """Coalesce concurrent predict requests into padded device batches."""

    def __init__(self, predict_fn, *, max_batch: int = 64,
                 max_wait: float = 0.002,
                 buckets: tuple[int, ...] = DEFAULT_BUCKETS):
        self.predict_fn = predict_fn
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.buckets = tuple(sorted(buckets))
        self._q: queue.Queue[_Request | None] = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="teacher-batcher")
        # Cumulative utilization counters (the registry `info` data source:
        # reference discovery/register.py:36-40 reserves the field for
        # "report job performance to the scheduler").
        self._stats_lock = threading.Lock()
        self._served_rows = 0
        self._served_requests = 0
        self._busy_s = 0.0
        self._started_at = time.monotonic()
        # Coalescing histogram: device-batch ROW count (pre-padding) ->
        # number of served groups. Whether concurrent client requests
        # actually merge (vs degenerate 1-request batches) is THE
        # efficiency question for a serving pool; the histogram makes it
        # observable instead of inferred.
        self._batch_hist: dict[int, int] = {}

    def start(self) -> "Batcher":
        self._thread.start()
        return self

    def submit(self, tensors: dict[str, np.ndarray]) -> _Request:
        rows = next(iter(tensors.values())).shape[0] if tensors else 0
        req = _Request(tensors=tensors, rows=rows)
        self._q.put(req)
        return req

    def _collect(self) -> list[_Request]:
        """One blocking pop, then drain whatever arrives within max_wait
        (bounded by max_batch rows)."""
        try:
            first = self._q.get(timeout=0.2)
        except queue.Empty:
            return []
        if first is None:
            return []
        group, rows = [first], first.rows
        deadline = time.monotonic() + self.max_wait
        while rows < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                req = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if req is None:
                break
            if rows + req.rows > self.max_batch:
                # Doesn't fit this round: run it in the next group.
                self._q.put(req)
                break
            group.append(req)
            rows += req.rows
        return group

    def _run(self) -> None:
        while not self._stop.is_set():
            group = self._collect()
            if not group:
                continue
            try:
                self._serve_group(group)
            except Exception as exc:
                log.exception("batch predict failed")
                for req in group:
                    if req.done.is_set():
                        # Heterogeneous requests already served (recursively)
                        # by _serve_group must not be retroactively failed.
                        continue
                    req.error = f"{type(exc).__name__}: {exc}"
                    req.done.set()

    def _serve_group(self, group: list[_Request]) -> None:
        names = list(group[0].tensors)
        for req in group[1:]:
            if list(req.tensors) != names:
                # Heterogeneous feeds can't coalesce; serve separately.
                self._serve_group([req])
        group = [g for g in group if list(g.tensors) == names]
        rows = sum(g.rows for g in group)
        bucket = pad_to_bucket(rows, self.buckets)
        feeds = {}
        for name in names:
            cat = np.concatenate([g.tensors[name] for g in group], axis=0)
            if bucket > rows:
                pad = np.zeros((bucket - rows,) + cat.shape[1:], cat.dtype)
                cat = np.concatenate([cat, pad], axis=0)
            feeds[name] = cat
        t0 = time.monotonic()
        outs = self.predict_fn(feeds)
        outs = {k: np.asarray(v) for k, v in outs.items()}
        with self._stats_lock:
            self._busy_s += time.monotonic() - t0
            self._served_rows += rows
            self._served_requests += len(group)
            self._batch_hist[rows] = self._batch_hist.get(rows, 0) + 1
        offset = 0
        for req in group:
            req.result = {k: v[offset:offset + req.rows]
                          for k, v in outs.items()}
            offset += req.rows
            req.done.set()

    def stats(self) -> dict:
        """Cumulative serving counters (consumed by TeacherRegistrar)."""
        with self._stats_lock:
            hist = dict(sorted(self._batch_hist.items()))
            groups = sum(hist.values())
            rows_mean = (sum(r * c for r, c in hist.items()) / groups
                         if groups else 0.0)
            return {"served_rows": self._served_rows,
                    "served_requests": self._served_requests,
                    "busy_s": round(self._busy_s, 4),
                    "uptime_s": round(time.monotonic() - self._started_at, 4),
                    "queue_depth": self._q.qsize(),
                    # JSON object keys are strings on the wire
                    "batch_rows_hist": {str(r): c for r, c in hist.items()},
                    "batch_rows_mean": round(rows_mean, 2)}

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=5.0)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        batcher: Batcher = self.server.batcher  # type: ignore[attr-defined]
        sock: socket.socket = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                meta, tensors = tensor_wire.recv_tensors(sock)
            except (tensor_wire.TensorWireError, OSError):
                return
            try:
                resp_meta, resp_tensors = self._dispatch(batcher, meta,
                                                         tensors)
            except Exception as exc:
                resp_meta = {"ok": False,
                             "error": f"{type(exc).__name__}: {exc}"}
                resp_tensors = {}
            try:
                tensor_wire.send_tensors(sock, resp_meta, resp_tensors)
            except OSError:
                return

    @staticmethod
    def _dispatch(batcher: Batcher, meta: dict, tensors: dict):
        op = meta.get("op")
        if op == "ping":
            return {"ok": True}, {}
        if op == "stats":
            return {"ok": True, **batcher.stats()}, {}
        if op == "predict":
            if not tensors:
                return {"ok": False, "error": "no feed tensors"}, {}
            req = batcher.submit(tensors)
            req.done.wait()
            if req.error is not None:
                return {"ok": False, "error": req.error}, {}
            return {"ok": True}, req.result
        return {"ok": False, "error": f"unknown op {op!r}"}, {}


class _ThreadingServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TeacherServer:
    """In-process handle: serve `predict_fn` on a TCP port.

    predict_fn: dict[str, np.ndarray] -> dict[str, np.ndarray]; typically a
    jitted model apply. Called only from the batcher thread, with batch
    sizes drawn from `buckets` — so jit compiles once per bucket.
    """

    def __init__(self, predict_fn, *, port: int = 0, host: str = "0.0.0.0",
                 max_batch: int = 64, max_wait: float = 0.002,
                 buckets: tuple[int, ...] = DEFAULT_BUCKETS):
        self.batcher = Batcher(predict_fn, max_batch=max_batch,
                               max_wait=max_wait, buckets=buckets)
        self._server = _ThreadingServer((host, port), _Handler)
        self._server.batcher = self.batcher  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._started = False

    def start(self) -> "TeacherServer":
        if self._started:
            return self
        self._started = True
        self.batcher.start()
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="teacher-serve").start()
        log.info("teacher server on :%d", self.port)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self.batcher.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class TeacherClient:
    """Blocking client of one teacher server (used by DistillReader's
    predict workers; the reference counterpart wraps paddle_serving_client,
    distill_worker.py:187-282)."""

    def __init__(self, endpoint: str, timeout: float = 30.0):
        from edl_tpu.utils.net import split_endpoint
        self.endpoint = endpoint
        host, port = split_endpoint(endpoint)
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def predict(self, feeds: dict[str, np.ndarray]
                ) -> dict[str, np.ndarray]:
        tensor_wire.send_tensors(self._sock, {"op": "predict"}, feeds)
        meta, tensors = tensor_wire.recv_tensors(self._sock)
        if not meta.get("ok"):
            raise tensor_wire.TensorWireError(
                meta.get("error", "predict failed"))
        return tensors

    def ping(self) -> bool:
        try:
            tensor_wire.send_tensors(self._sock, {"op": "ping"})
            meta, _ = tensor_wire.recv_tensors(self._sock)
            return bool(meta.get("ok"))
        except (tensor_wire.TensorWireError, OSError):
            return False

    def stats(self) -> dict:
        """Serving counters of the remote teacher (op: stats)."""
        tensor_wire.send_tensors(self._sock, {"op": "stats"})
        meta, _ = tensor_wire.recv_tensors(self._sock)
        if not meta.get("ok"):
            raise tensor_wire.TensorWireError(
                meta.get("error", "stats failed"))
        return {k: v for k, v in meta.items() if k != "ok"}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _build_model_predict(model_name: str, num_classes: int, params_path: str,
                         input_key: str, output_key: str,
                         input_shape: tuple[int, ...] = (32, 32, 3),
                         input_dtype: str = "float32"):
    """CLI helper: jitted zoo-model forward with random or restored params."""
    import jax
    import jax.numpy as jnp

    from edl_tpu import models as zoo
    from edl_tpu.train.classification import create_state
    import optax

    factory = zoo.get_model(model_name)
    model = factory(num_classes=num_classes)
    # Dense layers bind their kernel to the flattened input size, so init
    # must see the shape that will be served.
    state = create_state(model, jax.random.PRNGKey(0), (1,) + input_shape,
                         optax.identity(),
                         input_dtype=jnp.dtype(input_dtype))
    if params_path:
        from edl_tpu.train.checkpoint import CheckpointManager
        from edl_tpu.utils.fs import split_scheme
        # gs://... / hdfs://... params mirrors download before restore
        # (reference download_hdfs_file, distill/utils.py:18)
        scheme, rest = split_scheme(params_path)
        if scheme not in ("", "file"):
            import tempfile
            local = tempfile.mkdtemp(prefix="edl-teacher-params-")
            mgr = CheckpointManager(local, remote=params_path)
        else:
            mgr = CheckpointManager(rest if scheme == "file" else params_path)
        restored = mgr.restore(state)
        if restored is not None:
            state = restored[0]

    @jax.jit
    def forward(images):
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        return model.apply(variables, images, train=False)

    def predict(feeds):
        feed = jnp.asarray(feeds[input_key]).astype(jnp.dtype(input_dtype))
        return {output_key: np.asarray(forward(feed), np.float32)}

    return predict


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.distill.teacher_server",
        description="Serve a zoo model as a distill teacher")
    parser.add_argument("--model", default="mlp",
                        help="edl_tpu.models factory name (mlp, resnet50_vd, ...)")
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--params", default="",
                        help="checkpoint dir (or gs:///hdfs:// mirror URI) "
                             "to restore params from")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=23900)
    parser.add_argument("--input-key", default="image")
    parser.add_argument("--output-key", default="logits")
    parser.add_argument("--input-shape", default="32,32,3",
                        help="per-sample input shape, e.g. 28,28,1")
    parser.add_argument("--input-dtype", default="float32",
                        help="float32 for images, int32 for token ids")
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--max-wait-ms", type=float, default=2.0)
    args = parser.parse_args(argv)
    shape = tuple(int(x) for x in args.input_shape.split(","))
    predict = _build_model_predict(args.model, args.num_classes, args.params,
                                   args.input_key, args.output_key, shape,
                                   args.input_dtype)
    server = TeacherServer(predict, port=args.port, host=args.host,
                           max_batch=args.max_batch,
                           max_wait=args.max_wait_ms / 1000.0)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
