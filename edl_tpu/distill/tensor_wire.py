"""Import-compat shim: the binary tensor wire moved to the data layer
(`edl_tpu/data/tensor_wire.py`) so that ``data`` never imports
``distill`` (the layering contract in edl_tpu/analysis/layers.toml —
the wire is shared by the data server, distill serving, and p2p state
migration). Import from ``edl_tpu.data.tensor_wire`` in new code."""

from edl_tpu.data.tensor_wire import *  # noqa: F401,F403
from edl_tpu.data.tensor_wire import (MAGIC, MAX_HEADER, MAX_PAYLOAD,
                                      TensorWireError, recv_tensors,
                                      send_tensors)
