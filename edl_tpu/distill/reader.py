"""DistillReader: wrap a data reader, append teacher predictions.

The user-facing distill API — capability of the reference's DistillReader +
distill_worker pipeline (distill/distill_reader.py:68,313-374,
distill_worker.py:57-167,318-448,656-781), redesigned for the TPU host:

- the reference forks a reader process + N predict processes (Paddle's
  serving client demands it); our data plane is raw sockets + numpy, which
  release the GIL, so the pipeline is ONE process with a reader thread, a
  worker thread per assigned teacher, and a manage thread — same
  concurrency, no pickling/IPC tax, and the student's JAX dispatch thread
  is unaffected.

Invariants (the reference's poison-pill/exactly-once contract, proven in
tests/test_distill_reader.py under teacher kill/join):

  D1. every yielded batch carries predictions for exactly its own rows, in
      row order (out-of-order teacher replies are re-assembled by task id);
  D2. batches are yielded in reader order;
  D3. a teacher failure re-queues its in-flight tasks (bounded retries);
      nothing is lost or duplicated across teacher churn — with request
      pipelining (r6) a worker may own up to ``pipeline_depth`` tasks on
      one connection, and a mid-flight death re-queues every one of them
      exactly once;
  D4. the epoch terminates exactly when every sliced task has been served
      (feed-count == serve-count accounting, the poison-pill role);
  D5. backpressure: at most ``(pipeline_depth+1)*teachers + 2`` tasks in
      flight;
  D6. liveness: if NO connected teacher serves a task for
      ``deadman_timeout`` seconds while work is outstanding AND some
      teacher is known-dead, the epoch raises EdlDistillError naming the
      dead teachers — a permanently connect-refusing fixed teacher fails
      fast instead of hanging (the reference hangs in exactly this
      case). A discovery pool that is legitimately empty (scale-to-zero)
      keeps waiting for the balancer to reassign.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator

import numpy as np

from edl_tpu.distill.teacher_server import TeacherClient, TeacherRejected
from edl_tpu.utils import config
from edl_tpu.utils.backoff import Backoff
from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger
from edl_tpu.utils.timeline import timeline

log = get_logger("edl_tpu.distill.reader")


class EdlDistillError(EdlError):
    pass


@dataclass
class Task:
    task_id: int
    batch_id: int
    part: int            # slice index within the batch
    feeds: dict
    rows: int
    retries: int = 0
    # admission-shed rejections are accounted SEPARATELY from
    # connection-death retries: a shed is the pool saying "later", not
    # a fault, so it gets its own (larger) bounded budget
    shed_retries: int = 0


@dataclass
class _Batch:
    batch: dict
    n_parts: int = 0
    parts: dict = field(default_factory=dict)   # part -> predictions dict
    complete: bool = False


class _NopTeacherClient:
    """Fake teacher for tests/offline smoke (the reference's
    ``_NOP_PREDICT_TEST`` trick, distill_worker.py:34-42,306-315): runs the
    ENTIRE pipeline — slicing, workers, reordering, churn — with zero
    network. Predictions are zeros of shape (rows, dim) per predict name."""

    def __init__(self, endpoint: str, predicts: tuple[str, ...],
                 dim: int = 1, delay: float = 0.0):
        self.endpoint = endpoint
        self.predicts = predicts
        self.dim = dim
        self.delay = delay

    def predict(self, feeds: dict) -> dict:
        if self.delay:
            time.sleep(self.delay)
        rows = next(iter(feeds.values())).shape[0]
        return {name: np.zeros((rows, self.dim), np.float32)
                for name in self.predicts}

    def close(self) -> None:
        pass


class _PredictWorker(threading.Thread):
    """Owns one teacher connection; serves tasks from the shared queue.

    A task is owned from get() until either a successful out_queue.put or
    a re-queue — exactly-once across worker death (invariant D3). With a
    pipelining-capable client (``predict_async``) the worker keeps up to
    ``pipeline_depth`` requests in flight on its one connection, so
    teacher round-trip latency hides under the teacher's own compute and
    the student's train step; responses resolve FIFO and stay
    sequence-checked inside the client."""

    def __init__(self, pipeline: "_EpochPipeline", endpoint: str):
        super().__init__(daemon=True, name=f"distill-predict-{endpoint}")
        self.pipeline = pipeline
        self.endpoint = endpoint
        self.stop_event = threading.Event()
        self.broken = threading.Event()
        self.connected = threading.Event()  # client_factory succeeded

    def _check_outs(self, outs: dict) -> str | None:
        """Response contract checks; returns the failure reason."""
        p = self.pipeline
        missing = [k for k in p.predicts if k not in outs]
        if missing:
            return f"missing predicts {missing}"
        if p.sparse_predicts and p.compress_topk:
            # per-part top-k consistency: a teacher serving a different K
            # than negotiated would otherwise surface batches later as an
            # opaque np.concatenate shape error with no endpoint
            for name in outs:
                if not name.endswith((".idx", ".val")):
                    continue
                arr = outs[name]
                if arr.ndim >= 1 and arr.shape[-1] != p.compress_topk:
                    return (f"served top-{arr.shape[-1]} for {name!r} but "
                            f"the negotiated compress_topk is "
                            f"{p.compress_topk} (shape {arr.shape}); "
                            f"mixed --serve-topk across the pool?")
        return None

    def run(self) -> None:
        p = self.pipeline
        tl = timeline("distill.worker")
        try:
            client = p.client_factory(self.endpoint)
        except Exception as exc:
            log.warning("connect to teacher %s failed: %s", self.endpoint, exc)
            p.dead_teachers[self.endpoint] = f"connect: {exc}"
            self.broken.set()
            return
        self.connected.set()
        p.dead_teachers.pop(self.endpoint, None)
        depth = (p.pipeline_depth
                 if hasattr(client, "predict_async") else 1)
        inflight: deque = deque()   # [(task, handle-or-None)] send order
        # worker-owned (Backoff is not thread-safe by design); reset on
        # every successful serve so only CONSECUTIVE sheds escalate
        shed_backoff = Backoff(base=0.05, factor=2.0, max_delay=1.0)

        def die(exc: Exception, task: Task) -> None:
            """Connection-level failure: every in-flight task on this
            connection is lost; re-queue each exactly once (D3)."""
            task.retries += 1
            log.warning("teacher %s failed task %d (try %d): %s",
                        self.endpoint, task.task_id, task.retries, exc)
            p.dead_teachers[self.endpoint] = f"predict: {exc}"
            for t, _ in inflight:
                if t is not task:
                    t.retries += 1
            too_many = [t for t, _ in inflight
                        if t.retries > p.max_retries]
            if too_many:
                p.fail(f"task {too_many[0].task_id} failed "
                       f"{too_many[0].retries} times: {exc}")
            else:
                for t, _ in inflight:
                    p.in_queue.put(t)   # another worker re-serves them
            inflight.clear()
            self.broken.set()

        try:
            while not self.stop_event.is_set():
                # fill the window; block on intake only when idle
                while len(inflight) < depth:
                    try:
                        task = (p.in_queue.get(timeout=0.2) if not inflight
                                else p.in_queue.get_nowait())
                    except queue.Empty:
                        break
                    inflight.append((task, None))
                    if depth > 1:
                        try:
                            with tl.span("send"):
                                handle = client.predict_async(task.feeds)
                        except Exception as exc:
                            die(exc, task)
                            return
                        inflight[-1] = (task, handle)
                if not inflight:
                    continue
                task, handle = inflight[0]
                try:
                    with tl.span("predict"):
                        outs = (handle.result() if handle is not None
                                else client.predict(task.feeds))
                except TeacherRejected as rej:
                    # Typed admission shed — the connection is FINE; the
                    # teacher answered "come back later". Re-queue the
                    # task (a less-loaded teacher may take it) behind a
                    # jittered backoff floored at the server's
                    # retry_after hint, bounded by its own budget so a
                    # permanently-shedding pool fails typed instead of
                    # spinning forever. Never surfaces to the training
                    # step unless the budget is exhausted.
                    inflight.popleft()
                    task.shed_retries += 1
                    if task.shed_retries > p.shed_retry_budget:
                        p.fail(f"teacher pool shedding: task "
                               f"{task.task_id} rejected "
                               f"{task.shed_retries} times (budget "
                               f"{p.shed_retry_budget}): {rej}")
                        return
                    p.in_queue.put(task)
                    delay = max(shed_backoff.delay(), rej.retry_after_s)
                    if self.stop_event.wait(min(delay, 2.0)):
                        return
                    continue
                except Exception as exc:
                    die(exc, task)
                    return
                shed_backoff.reset()
                inflight.popleft()
                reason = self._check_outs(outs)
                if reason is not None:
                    p.fail(f"teacher {self.endpoint} {reason}")
                    return
                p.out_queue.put((task, outs))
        finally:
            # stopped mid-flight (teacher departed the desired set, epoch
            # teardown): hand unserved tasks back — they did not fail, so
            # no retry is charged
            for t, _ in inflight:
                p.in_queue.put(t)
            client.close()


class _EpochPipeline:
    """All shared state of one epoch's pipeline run."""

    def __init__(self, reader: "DistillReader"):
        self.predicts = reader._wire_predicts
        self.max_retries = reader.max_retries
        self.shed_retry_budget = reader.shed_retry_budget
        self.client_factory = reader._client_factory
        self.pipeline_depth = reader.pipeline_depth
        self.compress_topk = reader.compress_topk
        self.sparse_predicts = reader.sparse_predicts
        self.in_queue: queue.Queue = queue.Queue()
        self.out_queue: queue.Queue = queue.Queue()
        self.stop = threading.Event()
        self.error: list[str] = []
        n0 = max(1, len(reader._get_servers()))
        slots = self._window(n0)
        self.sem = threading.Semaphore(slots)
        self._sem_slots = slots   # manage-thread-owned bookkeeping
        self.reader_done = threading.Event()
        self.total_tasks = 0        # valid once reader_done is set
        self.total_batches = 0
        # deadman facts: serves counted by the consumer, dead-teacher
        # reasons recorded by workers, the clock owned by the manage
        # thread (reset whenever a connected worker is live or no work
        # is outstanding)
        self.served_count = 0
        self.dead_teachers: dict[str, str] = {}
        self.deadman_ts = time.monotonic()

    def fail(self, msg: str) -> None:
        self.error.append(msg)
        self.stop.set()

    def acquire_slot(self) -> bool:
        """Backpressure acquire that stays responsive to stop."""
        while not self.stop.is_set():
            if self.sem.acquire(timeout=0.1):
                return True
        return False

    def _window(self, n_teachers: int) -> int:
        """In-flight task window: per-connection pipelining depth + one
        task resolving at the head, per teacher, + slack (reduces to the
        reference's 2*teachers+2 at depth 1, distill_reader.py:215)."""
        return (self.pipeline_depth + 1) * max(1, n_teachers) + 2

    def resize_window(self, n_teachers: int) -> None:
        """Track the live teacher count so a teacher joining mid-epoch
        actually widens throughput. Called only from the manage thread;
        shrink is best-effort (never blocks the pipeline)."""
        target = self._window(n_teachers)
        while self._sem_slots < target:
            self.sem.release()
            self._sem_slots += 1
        while self._sem_slots > target and self.sem.acquire(blocking=False):
            self._sem_slots -= 1


_FMT_DICT = "dict"
_FMT_SAMPLE = "sample"
_FMT_SAMPLE_LIST = "sample_list"
_FMT_BATCH = "batch"


class DistillReader:
    """Wrap ``reader`` so iteration yields its batches + teacher predicts.

    Native format: dict batches (equal leading dim) — ``DataLoader.epoch``
    fits directly. The reference's three positional-slot reader formats
    (distill_reader.py:313-329, fetch: distill_worker.py:656-781) are
    supported as adapters over the same pipeline via ``ins=[...]`` +
    ``set_sample_generator`` / ``set_sample_list_generator`` /
    ``set_batch_generator``; iteration then yields the ORIGINAL structure
    with prediction slots appended (per-sample tuples / sample lists /
    stacked-array tuples respectively).

    Args:
      reader: callable returning an iterator of dict batches, or an
        iterable of such batches — or None when using the slot-format
        setters (the reference's construction order).
      feeds: batch keys sent to the teacher (dict format).
      ins: positional slot spec for the slot formats — a name per slot,
        ``None`` for passthrough slots not sent to the teacher (the
        reference's ``ins=['img', None]``).
      predicts: teacher output names appended to each batch.
      teachers: fixed teacher endpoint list (reference set_fixed_teacher);
        OR
      discovery: endpoints of discovery servers + ``service`` for dynamic
        teacher assignment. Both may instead be bound later via
        ``set_fixed_teacher`` / ``set_dynamic_teacher``.
      teacher_batch_size: rows per teacher RPC (reference default 16).
      deadman_timeout: seconds without any connected teacher serving a
        task (while work is outstanding) before the epoch raises
        EdlDistillError instead of waiting forever (invariant D6).
      pipeline_depth: in-flight requests kept per teacher connection
        (request pipelining; the client sequence-tags them and the server
        answers FIFO). Depth 1 restores strict request/response lockstep;
        clients without ``predict_async`` (test fakes) always run at
        depth 1. The reader window scales with it (invariant D5).
      compress_topk: negotiate top-k+fp16 logit compression with the
        teacher (~125x smaller response wire at 1000 classes, K=8; see
        teacher_server.compress_outputs). Default: transparently
        scatter-expanded back to dense fp32.
      sparse_predicts: with compress_topk, skip the expansion and yield
        ``name.idx``/``name.val`` pairs for sparse-aware losses
        (train/classification.make_sparse_distill_step). Dict format
        only.
      shed_retry_budget: bounded retries per task on teacher admission
        sheds (typed retry-after responses); past it the epoch raises
        EdlDistillError. Default EDL_TPU_SERVE_RETRY_BUDGET (8).
      tenant / priority: multi-tenant identity attached to every
        predict request — the teacher pool queues/sheds per (tenant,
        priority class); see distill/admission.py.

    Env: ``EDL_TPU_DISTILL_NOP=1`` swaps real connections for nop teachers
    (offline smoke; tests inject ``client_factory`` directly).
    """

    def __init__(self, reader=None, feeds: Iterable[str] | None = None,
                 predicts: Iterable[str] = (), *,
                 ins: Iterable[str | None] | None = None,
                 teachers: list[str] | None = None,
                 discovery: str | None = None, service: str | None = None,
                 teacher_batch_size: int = 16, max_retries: int = 3,
                 manage_interval: float = 0.5,
                 client_factory: Callable | None = None,
                 rpc_timeout: float = 30.0,
                 deadman_timeout: float = 60.0,
                 pipeline_depth: int = 4,
                 compress_topk: int = 0,
                 compress_values: str = "float16",
                 sparse_predicts: bool = False,
                 shed_retry_budget: int | None = None,
                 tenant: str = "", priority: str = ""):
        self.reader = reader
        self._format = _FMT_DICT
        self._ins = list(ins) if ins is not None else None
        if feeds is not None:
            self.feeds = tuple(feeds)
        elif self._ins is not None:
            self.feeds = tuple(n for n in self._ins if n is not None)
        else:
            self.feeds = ()
        self.predicts = tuple(predicts)
        self.sparse_predicts = sparse_predicts
        # what actually travels: sparse mode receives name.idx/name.val
        # pairs per predict (compress_outputs' naming) instead of the
        # dense tensor — the pipeline reassembles THESE keys.
        self._wire_predicts = tuple(
            f"{n}{suffix}" for n in self.predicts
            for suffix in ((".idx", ".val") if sparse_predicts else ("",)))
        self.teacher_batch_size = teacher_batch_size
        self.max_retries = max_retries
        self.manage_interval = manage_interval
        self.deadman_timeout = deadman_timeout
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.compress_topk = int(compress_topk)
        # bounded budget for admission-shed retries per task (satellite
        # of the r23 serving tier): sheds requeue behind a jittered
        # backoff, and past the budget the epoch fails TYPED instead of
        # retrying forever against a permanently-overloaded pool
        self.shed_retry_budget = (
            shed_retry_budget if shed_retry_budget is not None
            else config.env_int("EDL_TPU_SERVE_RETRY_BUDGET", 8))
        self._fixed_teachers = list(teachers) if teachers else None
        self._discovery_endpoints = discovery
        self._service = service
        self._discovery_client = None
        if sparse_predicts and not compress_topk:
            raise EdlDistillError("sparse_predicts requires compress_topk")
        if client_factory is None:
            if config.env_flag("EDL_TPU_DISTILL_NOP", False):
                client_factory = lambda ep: _NopTeacherClient(  # noqa: E731
                    ep, self._wire_predicts)
            else:
                client_factory = lambda ep: TeacherClient(  # noqa: E731
                    ep, timeout=rpc_timeout, compress_topk=compress_topk,
                    compress_values=compress_values,
                    expand=not sparse_predicts,
                    max_inflight=self.pipeline_depth,
                    tenant=tenant, priority=priority)
        self._client_factory = client_factory

    # -- teacher set --------------------------------------------------------

    def _get_servers(self) -> list[str]:
        if self._fixed_teachers is not None:
            return self._fixed_teachers
        if self._discovery_endpoints is None:
            raise EdlDistillError("need fixed `teachers` or `discovery` "
                                  "(set_fixed_teacher / set_dynamic_teacher)")
        if self._discovery_client is None:
            from edl_tpu.distill.discovery_client import DiscoveryClient
            self._discovery_client = DiscoveryClient(
                self._discovery_endpoints, self._service or "distill").start()
        return self._discovery_client.get_servers()

    def set_fixed_teacher(self, teachers: str | list[str]) -> "DistillReader":
        """Swap in a fixed teacher set — a comma-joined endpoint string
        or a list (reference set_fixed_teacher,
        distill_reader.py:279-291)."""
        if isinstance(teachers, str):
            teachers = [t for t in teachers.split(",") if t]
        self._fixed_teachers = list(teachers)
        return self

    # historical spelling used by earlier rounds' docs
    set_fixed_teachers = set_fixed_teacher

    def set_dynamic_teacher(self, discovery_servers: str | list[str],
                            teacher_service_name: str,
                            require_max_teacher: int = 0
                            ) -> "DistillReader":
        """Bind discovery-mode teacher assignment after construction
        (reference distill_reader.py:293-307). ``require_max_teacher`` is
        accepted for signature parity; the balancer assigns shares
        centrally (distill/balance.py), so a per-reader cap is not used.
        """
        if isinstance(discovery_servers, (list, tuple)):
            discovery_servers = ",".join(discovery_servers)
        self._fixed_teachers = None
        self._discovery_endpoints = discovery_servers
        self._service = teacher_service_name
        return self

    def close(self) -> None:
        if self._discovery_client is not None:
            self._discovery_client.stop()
            self._discovery_client = None

    # -- reference slot-format adapters -------------------------------------
    # (distill_reader.py:313-329 setters; slicing read_sample/
    # read_sample_list/read_batch and reassembly fetch_* in
    # distill_worker.py:481-781 — here both directions are thin
    # wrap/unwrap layers over the ONE dict pipeline, so all D1-D6
    # invariants apply to every format for free.)

    def set_sample_generator(self, reader) -> "DistillReader":
        """Reader yields ONE sample per iteration: a tuple/list of
        per-slot arrays matching ``ins``. Iteration then yields
        per-sample tuples ``(*slots, *predicts)``."""
        return self._set_slot_reader(reader, _FMT_SAMPLE)

    def set_sample_list_generator(self, reader) -> "DistillReader":
        """Reader yields a LIST of sample tuples per iteration; iteration
        yields lists of the same length with predict slots appended to
        each sample."""
        return self._set_slot_reader(reader, _FMT_SAMPLE_LIST)

    def set_batch_generator(self, reader) -> "DistillReader":
        """Reader yields a tuple of stacked per-slot arrays (leading dim
        = batch); iteration yields the same tuple with stacked predict
        arrays appended."""
        return self._set_slot_reader(reader, _FMT_BATCH)

    def _set_slot_reader(self, reader, fmt: str) -> "DistillReader":
        if self.reader is not None:
            raise EdlDistillError("reader has already been set")
        if self.sparse_predicts:
            raise EdlDistillError(
                "sparse_predicts is dict-format only (slot formats "
                "append dense prediction slots)")
        if self._ins is None:
            raise EdlDistillError(
                f"{fmt} readers are positional — construct DistillReader "
                f"with ins=[...] (None marks passthrough slots)")
        self.reader = reader
        self._format = fmt
        return self

    def _slot_keys(self) -> list[str]:
        return [n if n is not None else f"_slot{i}"
                for i, n in enumerate(self._ins)]

    def _wrap_slots(self, keys: list[str]) -> Iterator[dict]:
        """Slot-format input -> the pipeline's dict batches. Samples are
        grouped ``teacher_batch_size`` per dict batch (SAMPLE) or one
        incoming list/batch per dict batch, so reassembly-by-batch
        restores the original structure exactly."""
        src = self.reader() if callable(self.reader) else iter(self.reader)

        def pack(samples: list[tuple]) -> dict:
            return {k: np.stack([s[i] for s in samples])
                    for i, k in enumerate(keys)}

        if self._format == _FMT_SAMPLE:
            group: list[tuple] = []
            for sample in src:
                group.append(tuple(np.asarray(s) for s in sample))
                if len(group) == self.teacher_batch_size:
                    yield pack(group)
                    group = []
            if group:
                yield pack(group)
        elif self._format == _FMT_SAMPLE_LIST:
            for sample_list in src:
                yield pack([tuple(np.asarray(s) for s in sample)
                            for sample in sample_list])
        else:  # _FMT_BATCH
            for batch in src:
                yield {k: np.asarray(batch[i])
                       for i, k in enumerate(keys)}

    def _unwrap_slots(self, merged: dict, keys: list[str]) -> Iterator:
        """One pipeline dict batch -> original-structure output(s) with
        predict slots appended (the reference's fetch_sample/
        fetch_sample_list/fetch_batch reassembly)."""
        names = list(keys) + list(self.predicts)

        def sample(i: int) -> tuple:
            return tuple(merged[n][i] for n in names)

        rows = merged[keys[0]].shape[0]
        if self._format == _FMT_SAMPLE:
            for i in range(rows):
                yield sample(i)
        elif self._format == _FMT_SAMPLE_LIST:
            yield [sample(i) for i in range(rows)]
        else:  # _FMT_BATCH: stacked arrays, originals untouched
            yield tuple(merged[n] for n in names)

    # -- pipeline threads ---------------------------------------------------

    def _reader_thread(self, p: _EpochPipeline, src) -> None:
        tl = timeline("distill.reader")
        task_id = 0
        batch_id = 0
        try:
            it = src() if callable(src) else iter(src)
            for batch in it:
                if p.stop.is_set():
                    return
                rows = next(iter(batch.values())).shape[0]
                n_parts = -(-rows // self.teacher_batch_size)
                p.out_queue.put(("batch", batch_id, batch, n_parts))
                for part in range(n_parts):
                    lo = part * self.teacher_batch_size
                    hi = min(lo + self.teacher_batch_size, rows)
                    feeds = {k: np.ascontiguousarray(batch[k][lo:hi])
                             for k in self.feeds}
                    task = Task(task_id, batch_id, part, feeds, hi - lo)
                    task_id += 1
                    with tl.span("feed"):
                        if not p.acquire_slot():
                            return
                    p.in_queue.put(task)
                batch_id += 1
        except Exception as exc:
            p.fail(f"reader failed: {type(exc).__name__}: {exc}")
        finally:
            p.total_tasks = task_id
            p.total_batches = batch_id
            p.reader_done.set()

    def _manage_thread(self, p: _EpochPipeline,
                       workers: dict[str, _PredictWorker]) -> None:
        """Diff discovered teachers vs. worker pool (reference
        predict_manage_worker, distill_worker.py:57-161)."""
        while not p.stop.is_set():
            try:
                desired = set(self._get_servers())
            except Exception as exc:
                log.warning("teacher discovery failed: %s", exc)
                desired = set(workers)
            else:
                # Prune dead-teacher records for endpoints no longer in
                # the discovered set: a teacher that departed AND was
                # removed from assignment must not permanently trip the
                # scale-to-zero deadman below (the D6 docstring's
                # empty-pool promise). Fixed teachers stay in `desired`,
                # so their records — and the fail-fast — survive.
                for ep in list(p.dead_teachers):
                    if ep not in desired:
                        p.dead_teachers.pop(ep, None)
            for ep in list(workers):
                w = workers[ep]
                if ep not in desired or w.broken.is_set() \
                        or not w.is_alive():
                    w.stop_event.set()
                    if not w.is_alive():
                        workers.pop(ep)
            for ep in desired:
                if ep not in workers:
                    w = _PredictWorker(p, ep)
                    workers[ep] = w
                    w.start()
            p.resize_window(len(workers))
            # Epoch deadman: predict-time failures are bounded by
            # max_retries, but a teacher whose CONNECT always fails is
            # popped and re-created here every tick while queued tasks
            # wait forever (the reference hangs in exactly this case).
            # If no CONNECTED worker is live, work is outstanding, and
            # nothing has been served for deadman_timeout — fail,
            # naming the dead teachers. A discovery-mode pool that is
            # legitimately EMPTY (scale-to-zero, preemption) is not a
            # failure: the balancer will reassign, so the clock also
            # resets while no known-dead teacher exists.
            alive = any(w.is_alive() and w.connected.is_set()
                        and not w.broken.is_set()
                        for w in workers.values())
            outstanding = not (p.reader_done.is_set()
                               and p.served_count >= p.total_tasks)
            empty_pool_ok = (self._fixed_teachers is None
                             and not p.dead_teachers)
            if alive or not outstanding or empty_pool_ok:
                p.deadman_ts = time.monotonic()
            elif (time.monotonic() - p.deadman_ts
                  > self.deadman_timeout):
                dead = ", ".join(f"{ep} ({why})" for ep, why in
                                 sorted(p.dead_teachers.items())) \
                    or "none registered"
                p.fail(f"distill deadman: no live teacher served a task "
                       f"for {self.deadman_timeout:.0f}s with work "
                       f"outstanding; dead teachers: {dead}")
                return
            if p.stop.wait(self.manage_interval):
                return

    # -- the generator ------------------------------------------------------

    def __call__(self) -> Iterator:
        """One epoch. Dict format yields merged dict batches; slot
        formats yield the original structure with predicts appended."""
        if self.reader is None:
            raise EdlDistillError("must set a reader before iterating "
                                  "(constructor arg or set_*_generator)")
        if not self.feeds:
            raise EdlDistillError(
                "no teacher feeds configured — pass feeds=[...] (dict "
                "format) or ins=[...] with at least one named slot")
        if self._format == _FMT_DICT:
            yield from self._dict_epoch(self.reader)
            return
        keys = self._slot_keys()
        for merged in self._dict_epoch(lambda: self._wrap_slots(keys)):
            yield from self._unwrap_slots(merged, keys)

    def _dict_epoch(self, src) -> Iterator[dict]:
        p = _EpochPipeline(self)
        workers: dict[str, _PredictWorker] = {}
        threads = [
            threading.Thread(target=self._reader_thread, args=(p, src),
                             daemon=True, name="distill-reader"),
            threading.Thread(target=self._manage_thread, args=(p, workers),
                             daemon=True, name="distill-manage"),
        ]
        [t.start() for t in threads]
        tl = timeline("distill.fetch")

        pending: dict[int, _Batch] = {}
        next_yield = 0
        served_tasks = 0
        seen: set[tuple[int, int]] = set()
        try:
            while True:
                if p.error:
                    raise EdlDistillError("; ".join(p.error))
                if (p.reader_done.is_set() and served_tasks == p.total_tasks
                        and next_yield == p.total_batches):
                    return                      # D4: exactly-once epoch end
                try:
                    item = p.out_queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                if item[0] == "batch":
                    _, bid, batch, n_parts = item
                    entry = pending.setdefault(bid, _Batch(batch))
                    entry.batch = batch
                    entry.n_parts = n_parts
                    entry.complete = n_parts == 0
                else:
                    task, outs = item
                    key = (task.batch_id, task.part)
                    if key in seen:
                        raise EdlDistillError(f"duplicate serve for {key}")
                    seen.add(key)
                    served_tasks += 1
                    p.served_count = served_tasks  # deadman's progress fact
                    p.sem.release()
                    entry = pending.setdefault(task.batch_id, _Batch({}))
                    entry.parts[task.part] = outs
                    if entry.n_parts and len(entry.parts) == entry.n_parts:
                        entry.complete = True
                # D2: yield strictly in reader order.
                while next_yield in pending and pending[next_yield].complete:
                    entry = pending.pop(next_yield)
                    with tl.span("assemble"):
                        merged = dict(entry.batch)
                        for name in self._wire_predicts:
                            merged[name] = np.concatenate(
                                [entry.parts[i][name]
                                 for i in range(entry.n_parts)], axis=0) \
                                if entry.n_parts else np.zeros((0, 1))
                    yield merged
                    next_yield += 1
        finally:
            p.stop.set()
            # The manage thread may be mid-install/remove; join it first so
            # the worker dict is stable (and no worker is added after we
            # snapshot), then signal every worker.
            threads[1].join(timeout=2.0)
            for w in list(workers.values()):
                w.stop_event.set()
