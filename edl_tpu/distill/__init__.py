"""Elastic knowledge-distillation pillar.

Capability parity with the reference's headline feature (README.md:27-31,
74-92): student trainers pull teacher predictions over the network from an
elastic pool of inference servers, discovered and load-balanced through the
coordination store.

    teacher_server   — JAX batched-inference server (TPU/CPU)
    registrar        — CLI registering a teacher under a service name
    discovery_server — balancer daemon: client<->teacher assignment
    discovery_client — student-side registration + heartbeat + server cache
    balance          — the pure rebalance math
    reader           — DistillReader: wraps a data reader, appends teacher
                       predictions (the user-facing API; the reference's
                       three reader formats via ins=[...] +
                       set_*_generator)
    sharded_teacher  — one server process drives ALL local chips (dp/tp
                       mesh, device-side top-k serving)

The teacher wire supports negotiated top-k+fp16 compression
(`DistillReader(compress_topk=K)`, expanded transparently;
`sparse_predicts=True` + train.classification.make_sparse_distill_step
keeps targets sparse on device).
"""

from edl_tpu.distill.balance import ServiceBalance
from edl_tpu.distill.reader import DistillReader, EdlDistillError
from edl_tpu.distill.teacher_server import (TeacherClient, TeacherServer,
                                            compress_outputs,
                                            expand_outputs)

__all__ = ["ServiceBalance", "DistillReader", "EdlDistillError",
           "TeacherClient", "TeacherServer", "compress_outputs",
           "expand_outputs", "sharded_predict_fn"]


def __getattr__(name: str):
    # sharded_teacher pulls in jax + the mesh machinery at import time;
    # loading it lazily keeps `import edl_tpu.distill` working for
    # wire-only/CPU consumers (a student host that only needs
    # TeacherClient + numpy, a registrar sidecar, ...).
    if name in ("sharded_predict_fn", "sharded_teacher"):
        import importlib
        # NOT `from edl_tpu.distill import ...` — the fromlist machinery
        # re-enters this __getattr__ and recurses
        mod = importlib.import_module("edl_tpu.distill.sharded_teacher")
        return mod if name == "sharded_teacher" else mod.sharded_predict_fn
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
