"""Elastic knowledge-distillation pillar.

Capability parity with the reference's headline feature (README.md:27-31,
74-92): student trainers pull teacher predictions over the network from an
elastic pool of inference servers, discovered and load-balanced through the
coordination store.

    teacher_server   — JAX batched-inference server (TPU/CPU)
    registrar        — CLI registering a teacher under a service name
    discovery_server — balancer daemon: client<->teacher assignment
    discovery_client — student-side registration + heartbeat + server cache
    balance          — the pure rebalance math
    reader           — DistillReader: wraps a data reader, appends teacher
                       predictions (the user-facing API)
"""

from edl_tpu.distill.balance import ServiceBalance
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher_server import TeacherClient, TeacherServer

__all__ = ["ServiceBalance", "DistillReader", "TeacherClient",
           "TeacherServer"]
