"""Device mesh construction + sharding helpers.

This replaces the reference's process-per-GPU + NCCL world
(utils/edl_process.py spawns one trainer per GPU; Paddle fleet adds NCCL
allreduce ops to the graph): here a single process per host lays all local
(or a prefix of) devices into a named `jax.sharding.Mesh`, and jit-compiled
step functions get their gradient reductions from XLA's SPMD partitioner
riding ICI — no collective library, no per-device processes.

Axes (any may be size 1):
    dp — data parallel (batch dim)
    fsdp — parameter-sharded data parallel (zero-style)
    tp — tensor parallel (model dim)
    sp — sequence/context parallel (ring attention)
    ep — expert parallel (MoE expert tables + all-to-all dispatch)

Elasticity: a mesh is a pure function of the device list, so an elastic
resize is just `make_mesh(spec, n_devices=new_n)` after restart — checkpoint
state re-placed onto the new mesh by the sharding rules.

Multi-slice (hybrid ICI×DCN) topology: a multi-pod TPU job spans SLICES
joined by data-center network, with fast ICI only within a slice. The
capability analogue of the reference's hierarchical allreduce
(train_with_fleet.py:93 `use_hierarchical_allreduce`): `make_hybrid_mesh`
places the dp axis's MAJOR component across slices (the only axis whose
collectives cross DCN — one gradient allreduce per step, bandwidth-bound
and latency-tolerant) while fsdp/tp/sp — the chatty per-layer collectives
— stay entirely inside a slice on ICI. XLA's SPMD partitioner then emits
the two-level reduction from the device order alone.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The axis whose collectives are allowed to cross the slow DCN boundary.
DCN_AXIS = "dp"

# The expert-parallel axis. When present it carries the DCN dimension
# instead of dp: an MoE world's cross-slice traffic is the token
# all-to-all (train/comm.moe_all_to_all), so experts are laid out
# slice-local first and only overflow tokens cross DCN.
EP_AXIS = "ep"


def dcn_axis_of(axes) -> str:
    """The axis carrying the cross-slice (DCN) dimension for a set of
    mesh axis names: `ep` when present (expert dispatch owns the slow
    edge), else `dp`."""
    return EP_AXIS if EP_AXIS in axes else DCN_AXIS


@dataclass(frozen=True)
class SliceTopology:
    """Two-level device topology: n_slices pods of chips_per_slice chips,
    DCN between slices, ICI within. (1, n) is the flat single-slice
    world every other constructor assumes."""

    n_slices: int = 1
    chips_per_slice: int = 1

    @property
    def n_devices(self) -> int:
        return self.n_slices * self.chips_per_slice

    @property
    def is_multi_slice(self) -> bool:
        return self.n_slices > 1


def slice_groups(devices: list) -> list[list]:
    """Group devices by their hardware slice.

    Uses `device.slice_index` when the platform reports it (TPU
    multi-slice); devices without one (CPU test worlds, single-slice
    TPUs) all land in one group — callers emulating multi-slice on flat
    hardware pass an explicit SliceTopology instead.
    """
    groups: dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", None) or 0, []).append(d)
    return [groups[k] for k in sorted(groups)]


def detect_slice_topology(devices: list | None = None) -> SliceTopology:
    """SliceTopology reported by the hardware (flat world if it reports
    nothing). Raises on ragged slices — a hybrid mesh needs equal
    chips_per_slice."""
    if devices is None:
        devices = jax.devices()
    groups = slice_groups(devices)
    sizes = {len(g) for g in groups}
    if len(sizes) > 1:
        raise ValueError(
            f"ragged slices (chips per slice: {sorted(len(g) for g in groups)}"
            f") — cannot form a hybrid mesh")
    return SliceTopology(len(groups), len(devices) // len(groups))


@dataclass(frozen=True)
class MeshSpec:
    """Named logical axes and their sizes. -1 means 'absorb the rest'."""

    axes: dict[str, int] = field(default_factory=lambda: {"dp": -1})

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes

    def resolve_hybrid(self, topology: SliceTopology
                       ) -> tuple[dict[str, int], dict[str, int]]:
        """Split each axis size into (dcn, ici) factors against
        (n_slices, chips_per_slice) instead of a flat device count.

        Placement contract: exactly one axis crosses DCN — `ep` when the
        spec has one (expert dispatch owns the slow edge; experts are
        slice-local first and only overflow tokens cross), else `dp` —
        and its dcn factor is n_slices; every other axis (and the DCN
        axis's remaining factor) lives inside a slice. An elastic resize
        that changes EITHER level re-resolves cleanly: the per-slice
        axes never see the slice count, so adding a slice scales the
        DCN axis without re-factoring fsdp/tp/sp.
        """
        n_slices, per_slice = topology.n_slices, topology.chips_per_slice
        sizes = dict(self.axes)
        dcn_name = dcn_axis_of(sizes)
        if n_slices > 1 and dcn_name not in sizes:
            raise ValueError(
                f"multi-slice mesh needs a {DCN_AXIS!r} (or {EP_AXIS!r}) "
                f"axis to carry the DCN dimension; got axes {list(sizes)}")
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        # the DCN axis's in-slice factor: explicit sizes must carry the
        # n_slices multiple; a wildcard absorbs what the slice leaves.
        dcn_total = sizes.get(dcn_name, 1)
        if dcn_total != -1 and dcn_total % n_slices != 0:
            raise ValueError(
                f"{dcn_name}={dcn_total} not divisible by n_slices="
                f"{n_slices} ({dcn_name}'s major component spans the "
                f"slices)")
        ici_fixed = int(np.prod(
            [v for k, v in sizes.items() if v != -1 and k != dcn_name]))
        if dcn_total != -1:
            ici_fixed *= dcn_total // n_slices
        if wild:
            if per_slice % ici_fixed != 0:
                raise ValueError(
                    f"chips_per_slice={per_slice} not divisible by fixed "
                    f"in-slice axes of {sizes}")
            if wild[0] == dcn_name:
                sizes[dcn_name] = n_slices * (per_slice // ici_fixed)
            else:
                sizes[wild[0]] = per_slice // ici_fixed
        dcn = {k: (n_slices if k == dcn_name else 1) for k in sizes}
        ici = {k: (v // n_slices if k == dcn_name else v)
               for k, v in sizes.items()}
        if int(np.prod(list(ici.values()))) != per_slice:
            raise ValueError(
                f"mesh {sizes} != {n_slices} slices x {per_slice} chips")
        return dcn, ici


def make_mesh(spec: MeshSpec | None = None, n_devices: int | None = None,
              devices: list | None = None) -> Mesh:
    """Build a Mesh over the first n_devices (elastic prefix of the world)."""
    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"want {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    sizes = spec.resolve(len(devices))
    arr = np.array(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def make_hybrid_mesh(spec: MeshSpec | None = None,
                     topology: SliceTopology | None = None,
                     devices: list | None = None,
                     n_devices: int | None = None) -> Mesh:
    """Build a two-level ICI×DCN Mesh: dp's major dimension enumerates
    slices (DCN hops), everything else stays slice-local (ICI).

    Same shape contract as jax's `mesh_utils.create_hybrid_device_mesh`
    (global axis = dcn_factor * ici_factor, dcn major) without requiring
    the hardware to report a slice_index: `topology` may be passed
    explicitly to EMULATE a multi-slice layout on a flat device world
    (CPU tests, the dryrun), in which case slices are contiguous device
    chunks. With topology=None the hardware's slice_index decides —
    degenerating to a flat `make_mesh` on single-slice worlds.
    """
    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"want {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    if topology is None:
        topology = detect_slice_topology(devices)
    if topology.n_devices != len(devices):
        raise ValueError(
            f"topology {topology.n_slices}x{topology.chips_per_slice} != "
            f"{len(devices)} devices")
    if not topology.is_multi_slice:
        return make_mesh(spec, devices=devices)
    groups = slice_groups(devices)
    if len(groups) == topology.n_slices:
        ordered = [d for g in groups for d in g]
    elif len(groups) == 1:
        # flat hardware, emulated slices: contiguous chunks
        ordered = list(devices)
    else:
        raise ValueError(
            f"hardware reports {len(groups)} slices but topology asks for "
            f"{topology.n_slices}")
    dcn, ici = spec.resolve_hybrid(topology)
    names = list(spec.axes.keys())
    # (slice-major, chip-minor) grid -> (d0..dk, i0..ik) -> interleave so
    # each named axis is dcn-major x ici-minor -> merge the pairs. The
    # resulting device order makes dp's stride-per-slice the LARGEST, so
    # only dp collectives cross the slice boundary.
    grid = np.array(ordered, dtype=object).reshape(
        tuple(dcn[n] for n in names) + tuple(ici[n] for n in names))
    k = len(names)
    grid = grid.transpose(
        [x for pair in zip(range(k), range(k, 2 * k)) for x in pair])
    arr = grid.reshape(tuple(dcn[n] * ici[n] for n in names))
    return Mesh(arr, tuple(names))


def dp_comm_groups(n_slices: int, chips_per_slice: int
                   ) -> tuple[list[list[int]], list[list[int]]]:
    """(intra-slice, cross-slice) ``axis_index_groups`` over a
    slice-major dp axis.

    The manual-collective complement of `make_hybrid_mesh`: its device
    order makes dp index ``d = s * chips_per_slice + c``, so the
    intra-slice groups (dense ICI reduce-scatter / all-gather legs)
    are the C-contiguous chunks and the cross-slice groups (the DCN
    leg) are the stride-C columns. Static python lists — usable as
    ``axis_index_groups`` inside shard_map (train/comm.py's
    hierarchical reduction).
    """
    intra = [[s * chips_per_slice + c for c in range(chips_per_slice)]
             for s in range(n_slices)]
    cross = [[s * chips_per_slice + c for s in range(n_slices)]
             for c in range(chips_per_slice)]
    return intra, cross


def ep_comm_groups(n_slices: int, chips_per_slice: int
                   ) -> tuple[list[list[int]], list[list[int]]]:
    """(intra-slice, cross-slice) ``axis_index_groups`` over a
    slice-major ep axis — the expert-dispatch mirror of
    :func:`dp_comm_groups`.

    `make_hybrid_mesh` lays the ep axis out slice-major exactly like
    dp (ep index ``e = s * chips_per_slice + c``), so the group
    arithmetic is identical; what differs is what rides them: the
    intra groups carry the ICI all-to-all leg among a slice's
    co-resident experts (tokens reach the E/S experts in their own
    slice without touching DCN), and the cross groups carry only the
    OVERFLOW tokens routed to another slice's experts — the DCN leg of
    `train/comm.moe_all_to_all`, the one the int8 wire compresses.
    """
    if n_slices < 1 or chips_per_slice < 1:
        raise ValueError(
            f"ep_comm_groups needs positive factors, got "
            f"{n_slices}x{chips_per_slice}")
    return dp_comm_groups(n_slices, chips_per_slice)


def data_sharding(mesh: Mesh, batch_axes: tuple[str, ...] | None = None
                  ) -> NamedSharding:
    """Shard dim 0 (batch) over all data-like axes present in the mesh."""
    if batch_axes is None:
        batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    if not batch_axes:
        return replicated(mesh)
    return NamedSharding(mesh, P(batch_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, batch_axes: tuple[str, ...] | None = None):
    """Place a host-side batch pytree onto the mesh, sharded along dim 0."""
    sharding = data_sharding(mesh, batch_axes)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def form_global_batch(mesh: Mesh, local_batch,
                      batch_axes: tuple[str, ...] | None = None):
    """Assemble per-process host shards into one global device batch.

    Multi-host analogue of `shard_batch` (to which it degenerates in a
    single-process world): each process passes its own contiguous slice of
    the global batch (dim 0, ordered by process index) and gets back a
    global `jax.Array` sharded over the mesh's data axes — the input-feed
    half of the one-world contract the reference delegates to per-trainer
    data shards feeding per-GPU NCCL ranks.
    """
    if jax.process_count() == 1:
        return shard_batch(mesh, local_batch, batch_axes)
    sharding = data_sharding(mesh, batch_axes)
    if sharding.is_fully_replicated:
        # No data axes in the mesh: every process must hold the full batch,
        # so "local slice x nproc" arithmetic does not apply.
        return replicate_host_tree(mesh, local_batch)
    nproc = jax.process_count()

    def place(x):
        x = np.asarray(x)
        global_shape = (x.shape[0] * nproc,) + x.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape)

    return jax.tree.map(place, local_batch)


def replicate_host_tree(mesh: Mesh, tree):
    """Place an identical-on-every-process host pytree replicated on mesh.

    The restore half of multi-host checkpointing: every process
    deserializes the same host state, then re-places it as one global
    replicated array so a following jitted step sees committed global
    inputs (works on any process count; device_put handles both)."""
    return shard_batch(mesh, tree, batch_axes=())


def dp_size(mesh: Mesh) -> int:
    size = 1
    for axis in ("dp", "fsdp"):
        if axis in mesh.axis_names:
            size *= mesh.shape[axis]
    return size
