"""Device mesh construction + sharding helpers.

This replaces the reference's process-per-GPU + NCCL world
(utils/edl_process.py spawns one trainer per GPU; Paddle fleet adds NCCL
allreduce ops to the graph): here a single process per host lays all local
(or a prefix of) devices into a named `jax.sharding.Mesh`, and jit-compiled
step functions get their gradient reductions from XLA's SPMD partitioner
riding ICI — no collective library, no per-device processes.

Axes (any may be size 1):
    dp — data parallel (batch dim)
    fsdp — parameter-sharded data parallel (zero-style)
    tp — tensor parallel (model dim)
    sp — sequence/context parallel (ring attention)

Elasticity: a mesh is a pure function of the device list, so an elastic
resize is just `make_mesh(spec, n_devices=new_n)` after restart — checkpoint
state re-placed onto the new mesh by the sharding rules.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshSpec:
    """Named logical axes and their sizes. -1 means 'absorb the rest'."""

    axes: dict[str, int] = field(default_factory=lambda: {"dp": -1})

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dict(self.axes)
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes


def make_mesh(spec: MeshSpec | None = None, n_devices: int | None = None,
              devices: list | None = None) -> Mesh:
    """Build a Mesh over the first n_devices (elastic prefix of the world)."""
    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"want {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    sizes = spec.resolve(len(devices))
    arr = np.array(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def data_sharding(mesh: Mesh, batch_axes: tuple[str, ...] | None = None
                  ) -> NamedSharding:
    """Shard dim 0 (batch) over all data-like axes present in the mesh."""
    if batch_axes is None:
        batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    if not batch_axes:
        return replicated(mesh)
    return NamedSharding(mesh, P(batch_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, batch_axes: tuple[str, ...] | None = None):
    """Place a host-side batch pytree onto the mesh, sharded along dim 0."""
    sharding = data_sharding(mesh, batch_axes)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def form_global_batch(mesh: Mesh, local_batch,
                      batch_axes: tuple[str, ...] | None = None):
    """Assemble per-process host shards into one global device batch.

    Multi-host analogue of `shard_batch` (to which it degenerates in a
    single-process world): each process passes its own contiguous slice of
    the global batch (dim 0, ordered by process index) and gets back a
    global `jax.Array` sharded over the mesh's data axes — the input-feed
    half of the one-world contract the reference delegates to per-trainer
    data shards feeding per-GPU NCCL ranks.
    """
    if jax.process_count() == 1:
        return shard_batch(mesh, local_batch, batch_axes)
    sharding = data_sharding(mesh, batch_axes)
    if sharding.is_fully_replicated:
        # No data axes in the mesh: every process must hold the full batch,
        # so "local slice x nproc" arithmetic does not apply.
        return replicate_host_tree(mesh, local_batch)
    nproc = jax.process_count()

    def place(x):
        x = np.asarray(x)
        global_shape = (x.shape[0] * nproc,) + x.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape)

    return jax.tree.map(place, local_batch)


def replicate_host_tree(mesh: Mesh, tree):
    """Place an identical-on-every-process host pytree replicated on mesh.

    The restore half of multi-host checkpointing: every process
    deserializes the same host state, then re-places it as one global
    replicated array so a following jitted step sees committed global
    inputs (works on any process count; device_put handles both)."""
    return shard_batch(mesh, tree, batch_axes=())


def dp_size(mesh: Mesh) -> int:
    size = 1
    for axis in ("dp", "fsdp"):
        if axis in mesh.axis_names:
            size *= mesh.shape[axis]
    return size
