"""Multi-host world formation from the launcher's env contract.

Replaces the reference's NCCL world bootstrap (Paddle fleet reads
PADDLE_TRAINER_* env and broadcasts ncclUniqueId over sockets,
utils/edl_process.py:42-47): a trainer started by
`edl_tpu.collective.launch` calls `init_from_env()` once; on a multi-pod
cluster this runs `jax.distributed.initialize` against the rank-0 pod's
coordinator endpoint, after which `jax.devices()` spans all hosts and every
mesh built on it gets its collectives compiled over ICI/DCN by XLA — there
is no per-op communication library to configure.
"""

from __future__ import annotations

import jax

from edl_tpu.collective.job_env import TrainerEnv
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.parallel.distributed")

_initialized = False


def init_from_env(env: TrainerEnv | None = None) -> TrainerEnv:
    """Join the multi-host world described by the EDL_TPU_* env (no-op for
    single-pod jobs or repeat calls). Returns the parsed TrainerEnv."""
    global _initialized
    env = env or TrainerEnv.from_environ()
    if env.world_size > 1 and not _initialized:
        log.info("joining world: rank=%d/%d coordinator=%s",
                 env.rank, env.world_size, env.coordinator)
        jax.distributed.initialize(
            coordinator_address=env.coordinator,
            num_processes=env.world_size,
            process_id=env.rank)
        _initialized = True
    return env


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False
