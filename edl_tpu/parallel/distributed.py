"""Multi-host world formation from the launcher's env contract.

Replaces the reference's NCCL world bootstrap (Paddle fleet reads
PADDLE_TRAINER_* env and broadcasts ncclUniqueId over sockets,
utils/edl_process.py:42-47): a trainer started by
`edl_tpu.collective.launch` calls `init_from_env()` once (e.g.
`examples/multipod_demo.py`, the launcher's one-world trainer); on a
multi-pod cluster this runs `jax.distributed.initialize` against the
rank-0 pod's coordinator endpoint, after which `jax.devices()` spans all
hosts and every mesh built on it gets its collectives compiled over
ICI/DCN by XLA — there is no per-op communication library to configure.

On CPU (tests/CI) the cross-process data plane is the gloo TCP
collectives backend, selected automatically; on TPU, ICI/DCN needs no
selection.
"""

from __future__ import annotations

import os

import jax

from edl_tpu.collective.job_env import TrainerEnv
from edl_tpu.utils import config
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.parallel.distributed")

_initialized = False


def force_platform_from_env() -> None:
    """Apply JAX_PLATFORMS / JAX_NUM_CPU_DEVICES programmatically.

    Some environments (device-tunnel plugins registered from
    sitecustomize) override env-var platform selection, so a trainer that
    must run on host CPUs (tests, CI) applies the same contract through
    jax.config before the backend initializes. No-op once a backend
    exists or when the vars are unset.
    """
    if _backends_initialized():
        # config.update("jax_platforms") after backend init silently
        # resets the backend cache (e.g. an 8-device CPU test world
        # collapses to the 1-chip tunnel device) — enforce the no-op-
        # once-initialized contract explicitly.
        return
    plat = os.environ.get("JAX_PLATFORMS")
    ndev = os.environ.get("JAX_NUM_CPU_DEVICES", "").strip()
    try:
        ndev_i = int(ndev) if ndev else None
    except ValueError:
        log.warning("ignoring malformed JAX_NUM_CPU_DEVICES=%r", ndev)
        ndev_i = None
    try:
        if plat:
            jax.config.update("jax_platforms", plat)
        if ndev_i is not None:
            try:
                jax.config.update("jax_num_cpu_devices", ndev_i)
            except AttributeError:
                # jax < 0.5: no such option; the XLA flag is the portable
                # spelling, read at backend init (same fallback as
                # tests/conftest.py)
                if "xla_force_host_platform_device_count" not in \
                        os.environ.get("XLA_FLAGS", ""):
                    os.environ["XLA_FLAGS"] = (
                        os.environ.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count="
                          f"{ndev_i}").strip()
    except RuntimeError:  # backend already up — leave it be
        pass


def _backends_initialized() -> bool:
    try:
        from jax._src import xla_bridge
        return xla_bridge.backends_are_initialized()
    except Exception:  # private API moved — fall back to "assume not"
        return False


def enable_compilation_cache(cache_dir: str | None = None) -> bool:
    """Point XLA's persistent compilation cache at ``cache_dir`` (default
    ``$EDL_TPU_COMPILE_CACHE_DIR``; no-op when unset).

    The elastic-downtime lever: a stop-resume re-formation re-jits every
    program from scratch, and for a world whose shape (and therefore
    compiled programs) did NOT change, that recompile dominates
    kill->first-step time. With the cache on a persistent path, the
    re-formed trainer loads the previous generation's executables
    instead of rebuilding them. Thresholds drop to 0 so even quick
    compiles persist — an elastic restart replays ALL of them at once.
    """
    cache_dir = cache_dir or config.env_str("EDL_TPU_COMPILE_CACHE_DIR")
    if not cache_dir:
        return False
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                          ("jax_persistent_cache_min_entry_size_bytes", 0)):
            try:
                jax.config.update(knob, val)
            except AttributeError:  # older jax: keep its default threshold
                pass
    except AttributeError:
        log.warning("this jax has no persistent compilation cache — "
                    "EDL_TPU_COMPILE_CACHE_DIR ignored")
        return False
    log.info("persistent XLA compilation cache at %s", cache_dir)
    return True


def init_from_env(env: TrainerEnv | None = None) -> TrainerEnv:
    """Join the multi-host world described by the EDL_TPU_* env (no-op for
    single-pod jobs or repeat calls). Returns the parsed TrainerEnv."""
    global _initialized
    env = env or TrainerEnv.from_environ()
    enable_compilation_cache()  # re-formed worlds skip unchanged re-jits
    if env.world_size > 1 and not _initialized:
        force_platform_from_env()
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # Multi-process CPU needs an explicit inter-process collectives
            # implementation; TPU rides ICI/DCN without one.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        log.info("joining world: rank=%d/%d coordinator=%s",
                 env.rank, env.world_size, env.coordinator)
        jax.distributed.initialize(
            coordinator_address=env.coordinator,
            num_processes=env.world_size,
            process_id=env.rank)
        _initialized = True
    return env


def slice_topology(env: TrainerEnv | None = None,
                   devices: list | None = None):
    """Derive the job's ICI×DCN SliceTopology.

    Priority: the env contract (EDL_TPU_SLICES > 1 — the operator pinned
    the slice count on the job, e.g. a GKE multi-slice JobSet) beats
    hardware auto-detect (`jax.devices()` slice_index, present on TPU
    multi-slice), which beats the flat single-slice default. The env
    path lets CPU worlds and single-slice dev boxes EMULATE multi-slice
    for tests/dryruns; the detect path needs no configuration at all.
    """
    from edl_tpu.parallel.mesh import SliceTopology, detect_slice_topology

    env = env or TrainerEnv.from_environ()
    if devices is None:
        devices = jax.devices()
    if env.n_slices > 1:
        if len(devices) % env.n_slices != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible by "
                f"EDL_TPU_SLICES={env.n_slices}")
        return SliceTopology(env.n_slices, len(devices) // env.n_slices)
    detected = detect_slice_topology(devices)
    return detected if detected.is_multi_slice else SliceTopology(
        1, len(devices))


def make_mesh_from_env(spec=None, env: TrainerEnv | None = None,
                       devices: list | None = None):
    """The mesh a launched trainer should train on: hybrid ICI×DCN when
    the world is (or is declared) multi-slice, flat otherwise. Elastic
    resizes re-form correctly because MeshSpec resolves against
    (n_slices, chips_per_slice), not a flat device count."""
    from edl_tpu.parallel import mesh as mesh_lib

    topo = slice_topology(env, devices)
    if topo.is_multi_slice:
        return mesh_lib.make_hybrid_mesh(spec, topo, devices=devices)
    return mesh_lib.make_mesh(spec, devices=devices)


def reform_world(env: TrainerEnv) -> TrainerEnv:
    """Tear down the collective layer and re-form it with a NEW topology
    — the mesh-re-formation primitive of the reform state machine
    (collective/reform.py): a surviving process keeps running, drops
    only `jax.distributed`, and rejoins the re-formed world under its
    new (rank, world, coordinator). The persistent compilation cache is
    (re)enabled first so the re-formed world's unchanged programs skip
    their re-jits — a genuinely-new shape costs exactly one compile.

    Single-process worlds (world_size <= 1) only tear down; there is
    nothing to rejoin — the caller rebuilds its local mesh and the
    in-process jit cache carries the re-jit story.

    Failures (a coordinator that never comes up, a runtime that cannot
    re-initialize) surface as the typed ``EdlError`` the reform
    machine's mesh-reform phase downgrades on — never a bare crash.
    """
    from edl_tpu.utils.exceptions import EdlError
    global _initialized
    enable_compilation_cache()
    try:
        if _initialized:
            jax.distributed.shutdown()
            _initialized = False
        if env.world_size > 1:
            log.info("re-forming world: rank=%d/%d coordinator=%s",
                     env.rank, env.world_size, env.coordinator)
            jax.distributed.initialize(
                coordinator_address=env.coordinator,
                num_processes=env.world_size,
                process_id=env.rank)
            _initialized = True
    except Exception as exc:  # noqa: BLE001 — typed for the reform
        # machine's mesh-reform downgrade (stop-resume), never a crash
        raise EdlError(f"mesh re-formation failed: {exc}") from exc
    return env


def is_initialized() -> bool:
    return _initialized


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False
