"""Parameter/activation sharding rules (logical-axis style).

The TPU-native replacement for everything the reference delegates to NCCL
process groups (SURVEY.md §2.3): parameters carry *logical* axis names, a
rule table maps logical names to mesh axes, and `jax.jit` + XLA's SPMD
partitioner materialize the collectives (all-gather for fsdp params,
reduce-scatter/all-reduce for grads, all-to-all for tp boundaries) over ICI.

Rules are `(logical_name, mesh_axis | None)` pairs, first match wins —
the flax `logical_to_mesh` convention.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rule table for the transformer family. Logical names:
#   batch   — batch dim of activations
#   seq     — sequence dim (ring-attention shards live here)
#   vocab   — embedding table rows
#   embed   — model dim
#   heads   — attention heads
#   kv      — per-head dim
#   mlp     — feed-forward hidden dim
DEFAULT_RULES: tuple[tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
    ("vocab", "tp"),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("kv", None),
    ("mlp", "tp"),
    # Embedding-table axes. The token-id gather cannot be partitioned
    # along its vocab (operand) dim — XLA falls back to "involuntary full
    # rematerialization", all-gathering the whole table every step — so
    # the table shards along the embedding dim only (tp); the gather then
    # partitions trivially and the cheap reshard is on the (b, s, d)
    # activations, not the (V, d) table.
    ("vocab_table", None),
    ("embed_table", "tp"),
    # MoE expert tables: the leading expert dim shards over ep, so the
    # checkpoint index carries each table as ep-sharded leaves and the
    # cross-mesh resharding planner (train/sharded_checkpoint.py +
    # collective/migration.py) re-shards experts on an ep resize like
    # any other sharded state. The router's expert dim stays replicated
    # (expert_router) — every chip routes against all experts.
    ("expert", "ep"),
    ("expert_router", None),
)


def logical_to_spec(logical: Sequence[str | None],
                    rules: Sequence[tuple[str, Any]] = DEFAULT_RULES,
                    mesh: Mesh | None = None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    Mesh axes not present in `mesh` (or of size 1) are dropped so one rule
    table serves every mesh shape — the elasticity hook: resize the mesh and
    re-derive shardings, no rule edits.
    """
    taken: set[str] = set()
    out: list[Any] = []
    for name in logical:
        axis = None
        if name is not None:
            for rule_name, rule_axis in rules:
                if rule_name == name:
                    axis = rule_axis
                    break
        if axis is None:
            out.append(None)
            continue
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        if mesh is not None:
            axes = tuple(a for a in axes
                         if a in mesh.axis_names and mesh.shape[a] > 1)
        axes = tuple(a for a in axes if a not in taken)
        taken.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def param_shardings(mesh: Mesh, abstract_params: Any,
                    rules: Sequence[tuple[str, Any]] = DEFAULT_RULES) -> Any:
    """NamedShardings for a pytree of flax Partitioned/plain leaves.

    Leaves carrying flax `Partitioned` metadata (`.names`) get their logical
    names mapped through `rules`; plain leaves are replicated.
    """

    def one(leaf):
        names = getattr(leaf, "names", None)
        if names is None:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, logical_to_spec(names, rules, mesh))

    return jax.tree.map(one, abstract_params,
                        is_leaf=lambda x: hasattr(x, "names"))


def init_sharded(init_fn, mesh: Mesh,
                 rules: Sequence[tuple[str, Any]] = DEFAULT_RULES) -> Any:
    """Run a flax `init` thunk with params materialized ALREADY sharded.

    `jax.eval_shape` gives the abstract boxed variable tree; logical names
    become NamedShardings; the real init runs under jit with those
    out_shardings so each device only materializes its own parameter
    shards — no full replica ever exists in HBM (how multi-billion-param
    states fit, and how elastic restore re-places shards on a new mesh).
    Returns the unboxed variables dict.
    """
    from flax.core import meta

    abstract = jax.eval_shape(init_fn)
    shardings = param_shardings(mesh, abstract, rules)
    return jax.jit(lambda: meta.unbox(init_fn()),
                   out_shardings=shardings)()


def dp_row_sharding(mesh: Mesh) -> NamedSharding:
    """One distinct row per dp position: ``(W, ...)`` arrays laid out
    ``P('dp')``. The placement of the comm plane's per-chip
    error-feedback residuals (train/comm.py) — each chip owns exactly
    its own row, so a shard_map over dp sees its local ``(1, ...)``
    block and no residual ever crosses a link."""
    return NamedSharding(mesh, P("dp"))


def constrain(x: jax.Array, logical: Sequence[str | None],
              mesh: Mesh | None = None,
              rules: Sequence[tuple[str, Any]] = DEFAULT_RULES) -> jax.Array:
    """with_sharding_constraint by logical names (no-op without a mesh)."""
    if mesh is None:
        return x
    spec = logical_to_spec(logical, rules, mesh)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
