"""Ring attention — sequence/context parallelism over a mesh axis.

Net-new capability (the reference has none — SURVEY.md §5 "Long-context /
sequence parallelism: ABSENT"): shards the sequence dim of q/k/v over the
`sp` mesh axis and rotates k/v blocks around the ring with `ppermute` while
accumulating flash-style (running max / running denominator), so attention
over sequence length S costs O(S/n) memory per device and the k/v transfer
overlaps with the block matmuls riding ICI.

Algorithm (Liu et al., Ring Attention; blockwise softmax accumulation):
each of the n steps computes q_local x k_block^T on the MXU in fp32,
rescales the running (o, l, m) accumulators, then ppermutes the k/v block
to the next device. Causal masking uses global positions derived from
`axis_index`, so step blocks that are entirely in the future contribute
nothing (their probabilities underflow to 0 via the -1e30 mask constant).

Autodiff: implemented with `lax.scan` (reverse-differentiable); the
backward pass replays the ring in reverse via transposed ppermute, which
JAX derives automatically.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _local_ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          axis_name: str, causal: bool, scale: float
                          ) -> jax.Array:
    """Per-shard body under shard_map. q/k/v: (B, S_local, H, D)."""
    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    s_local = q.shape[1]
    b, _, h, d = q.shape

    q32 = q.astype(jnp.float32)
    q_pos = my_index * s_local + jnp.arange(s_local)          # (S,)

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        src = (my_index - i) % axis_size                      # block origin
        kv_pos = src * s_local + jnp.arange(s_local)
        # (B, H, Sq, Sk) scores in fp32 — MXU matmul with fp32 accumulate.
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])                     # (B,H,Sq,Sk)
        correction = jnp.exp(m - m_new)
        l = l * correction + jnp.sum(p, axis=-1)
        o = o * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        k_next = lax.ppermute(
            k_blk, axis_name,
            perm=[(j, (j + 1) % axis_size) for j in range(axis_size)])
        v_next = lax.ppermute(
            v_blk, axis_name,
            perm=[(j, (j + 1) % axis_size) for j in range(axis_size)])
        return (o, m_new, l, k_next, v_next), None

    o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    m0 = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    (o, _, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(axis_size))
    o = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)     # (B,S,H,D)


def _local_ring_flash(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool, scale: float
                      ) -> jax.Array:
    """Ring body whose per-block attention is the flash kernel.

    Each ring step runs `flash_attention_lse` on (q_local, kv_block) —
    O(S_local * flash_block) live memory instead of the dense body's
    S_local^2 score block — and merges the normalized partial outputs
    by their log-sum-exp weights (the exact blockwise-softmax combine).
    Global causality decides the block's kernel mode: past blocks are
    dense-allowed (causal=False), the diagonal block is causal, future
    blocks contribute nothing.
    """
    from edl_tpu.ops.flash_attention import flash_attention_lse

    axis_size = lax.psum(1, axis_name)
    my_index = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape

    def past(q, kb, vb):
        o, lse = flash_attention_lse(q, kb, vb, causal=False, scale=scale)
        # fp32 so all switch branches (incl. `future`) agree for bf16 io
        return o.astype(jnp.float32), lse

    def diag(q, kb, vb):
        o, lse = flash_attention_lse(q, kb, vb, causal=True, scale=scale)
        return o.astype(jnp.float32), lse

    def future(q, kb, vb):
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.full((b, s_local, h), _NEG_INF, jnp.float32))

    def combine(o, lse, o_b, lse_b):
        o_b = o_b.astype(jnp.float32)
        m = jnp.maximum(lse, lse_b)
        safe = m > _NEG_INF / 2
        w1 = jnp.where(safe, jnp.exp(lse - m), 0.0)
        w2 = jnp.where(safe, jnp.exp(lse_b - m), 0.0)
        den = jnp.maximum(w1 + w2, 1e-30)
        o_new = (o * w1[..., None] + o_b * w2[..., None]) / den[..., None]
        lse_new = jnp.where(safe, m + jnp.log(den), m)
        return o_new, lse_new

    def step(carry, i):
        o, lse, k_blk, v_blk = carry
        src = (my_index - i) % axis_size
        case = jnp.where(src == my_index, 0,
                         jnp.where(src < my_index, 1, 2))
        if causal:
            o_b, lse_b = lax.switch(case, (diag, past, future),
                                    q, k_blk, v_blk)
        else:
            o_b, lse_b = past(q, k_blk, v_blk)
        o, lse = combine(o, lse, o_b, lse_b)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        return (o, lse, lax.ppermute(k_blk, axis_name, perm=perm),
                lax.ppermute(v_blk, axis_name, perm=perm)), None

    o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    lse0 = jnp.full((b, s_local, h), _NEG_INF, jnp.float32)
    (o, _, _, _), _ = lax.scan(step, (o0, lse0, k, v),
                               jnp.arange(axis_size))
    return o.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh, sp_axis: str = "sp",
                   batch_axes: Sequence[str] = ("dp", "fsdp"),
                   head_axis: str = "tp", causal: bool = True,
                   scale: float | None = None,
                   use_flash: bool = False) -> jax.Array:
    """Global-view ring attention. q/k/v: (B, S, H, D), S sharded on sp_axis.

    Call under jit with global arrays; shard_map splits them so each device
    holds its sequence block, heads additionally sharded over `head_axis`.
    `use_flash=True` runs the flash kernel per block pair (O(S_local*blk)
    memory instead of S_local^2; enable on TPU for long local blocks).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    batch = tuple(a for a in batch_axes
                  if a in mesh.axis_names and mesh.shape[a] > 1) or None
    heads = head_axis if (head_axis in mesh.axis_names
                          and mesh.shape[head_axis] > 1) else None
    spec = P(batch, sp_axis, heads)
    body = _local_ring_flash if use_flash else _local_ring_attention
    fn = functools.partial(body, axis_name=sp_axis,
                           causal=causal, scale=scale)
    from edl_tpu.parallel.compat import shard_map
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: float | None = None
                    ) -> jax.Array:
    """Plain (single-device / XLA-partitioned) reference attention.

    Used when the mesh has no sp axis, and as the numerical oracle in
    tests. Same fp32-accumulate contract as the ring path.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)
