"""jax API compatibility shims.

The repo targets the modern ``jax.shard_map`` spelling; older jax
releases (< 0.5) ship it as ``jax.experimental.shard_map.shard_map``
with ``check_rep`` instead of ``check_vma``. Call sites import the one
symbol here so the version split lives in exactly one place.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
