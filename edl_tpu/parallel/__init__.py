from edl_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    data_sharding,
    replicated,
    shard_batch,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "data_sharding",
    "replicated",
    "shard_batch",
]
