from edl_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    data_sharding,
    replicated,
    shard_batch,
)
from edl_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    init_sharded,
    logical_to_spec,
    param_shardings,
)
from edl_tpu.parallel import ring_attention  # module (fn: ring_attention.ring_attention)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "data_sharding",
    "replicated",
    "shard_batch",
    "DEFAULT_RULES",
    "constrain",
    "init_sharded",
    "logical_to_spec",
    "param_shardings",
    "ring_attention",
]
