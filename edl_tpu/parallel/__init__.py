from edl_tpu.parallel.mesh import (
    MeshSpec,
    SliceTopology,
    detect_slice_topology,
    make_hybrid_mesh,
    make_mesh,
    data_sharding,
    form_global_batch,
    replicate_host_tree,
    replicated,
    shard_batch,
)
from edl_tpu.parallel.distributed import (
    init_from_env,
    make_mesh_from_env,
    slice_topology,
)
from edl_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    init_sharded,
    logical_to_spec,
    param_shardings,
)
from edl_tpu.parallel import ring_attention  # module (fn: ring_attention.ring_attention)

__all__ = [
    "MeshSpec",
    "SliceTopology",
    "detect_slice_topology",
    "make_hybrid_mesh",
    "make_mesh",
    "make_mesh_from_env",
    "slice_topology",
    "data_sharding",
    "form_global_batch",
    "init_from_env",
    "replicate_host_tree",
    "replicated",
    "shard_batch",
    "DEFAULT_RULES",
    "constrain",
    "init_sharded",
    "logical_to_spec",
    "param_shardings",
    "ring_attention",
]
