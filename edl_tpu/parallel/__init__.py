from edl_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    data_sharding,
    form_global_batch,
    replicate_host_tree,
    replicated,
    shard_batch,
)
from edl_tpu.parallel.distributed import init_from_env
from edl_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    init_sharded,
    logical_to_spec,
    param_shardings,
)
from edl_tpu.parallel import ring_attention  # module (fn: ring_attention.ring_attention)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "data_sharding",
    "form_global_batch",
    "init_from_env",
    "replicate_host_tree",
    "replicated",
    "shard_batch",
    "DEFAULT_RULES",
    "constrain",
    "init_sharded",
    "logical_to_spec",
    "param_shardings",
    "ring_attention",
]
