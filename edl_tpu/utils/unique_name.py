"""Process-unique name generation (reference utils/unique_name.py:16)."""

from __future__ import annotations

import itertools
import os
import threading
import time

_counters: dict[str, itertools.count] = {}
_lock = threading.Lock()


def generate(prefix: str) -> str:
    """Return ``prefix_N`` with a per-prefix monotonically increasing N."""
    with _lock:
        counter = _counters.setdefault(prefix, itertools.count())
        return f"{prefix}_{next(counter)}"


def client_id(channel: int = 0) -> str:
    """Globally-unique-ish client identity: ip-pid-channel-timestamp.

    Capability parity: reference distill/discovery_client.py:169-175.
    """
    from edl_tpu.utils.net import host_ip

    return f"{host_ip()}-{os.getpid()}-{channel}-{time.monotonic_ns()}"
