"""Pluggable remote filesystem for checkpoints and teacher params.

Capability of the reference's remote-FS story (distill/utils.py:18
`download_hdfs_file` fetches teacher serving configs from HDFS;
doc/fault_tolerance.md:30-45 has rank 0 upload checkpoints to a shared
store that every restarted pod downloads), re-designed for this stack:

- `FileSystem` is a tiny transfer interface (exists / listdir / upload /
  download / delete) over *directory trees*, because checkpoints here are
  atomic directories (`ckpt-{version}`), not single files.
- `LocalFS` backs `file://` and bare paths — the shared-NFS deployment.
- `CommandFS` shells out to a storage CLI (`gsutil` for `gs://`, `hdfs
  dfs` for `hdfs://`) so cloud object stores work with zero Python
  dependencies, the same way the reference drives HDFS through Paddle's
  external client rather than a native protocol implementation. The
  command table is injectable, which is also how tests exercise the
  remote path without any cloud (a `cp -r`-backed fake).
- `mirror_checkpoint` / `fetch_latest_checkpoint` bolt the transfer onto
  `CheckpointManager`'s local-atomic layout: rank 0 uploads the sealed
  version dir then overwrites a tiny `LATEST` marker (marker-last ==
  remote readers never see a half-uploaded version), and a cold pod
  downloads the marked version before restoring locally.

`fetch_file` is the C15 analogue for single files (teacher params,
serving configs): download a `scheme://` URI into a local cache dir,
no-op for local paths.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import tempfile
from typing import Sequence

from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.utils.fs")


class EdlFsError(EdlError):
    pass


def split_scheme(uri: str) -> tuple[str, str]:
    """("gs", "bucket/path") for "gs://bucket/path"; ("", uri) for paths."""
    if "://" in uri:
        scheme, rest = uri.split("://", 1)
        return scheme, rest
    return "", uri


class FileSystem:
    """Transfer interface over directory trees at string URIs."""

    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def listdir(self, uri: str) -> list[str]:
        """Child basenames of a directory URI (empty if absent)."""
        raise NotImplementedError

    def upload(self, local: str, uri: str) -> None:
        """Recursively copy local file/dir to uri (parents created)."""
        raise NotImplementedError

    def download(self, uri: str, local: str) -> None:
        """Recursively copy uri to local path (parents created)."""
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        """Remove file/dir at uri; no error if absent."""
        raise NotImplementedError

    def read_text(self, uri: str) -> str:
        # download into a private dir (a predictable pre-claimed file name
        # would let another party plant content, e.g. a LATEST value)
        tmpdir = tempfile.mkdtemp(prefix="edl-fs-")
        try:
            tmp = os.path.join(tmpdir, "f")
            self.download(uri, tmp)
            with open(tmp) as f:
                return f.read()
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)

    def write_text(self, uri: str, text: str) -> None:
        fd, tmp = tempfile.mkstemp(prefix="edl-fs-")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text)
            self.upload(tmp, uri)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)


class LocalFS(FileSystem):
    """file:// and bare paths (local disk or mounted NFS)."""

    @staticmethod
    def _path(uri: str) -> str:
        scheme, rest = split_scheme(uri)
        if scheme not in ("", "file"):
            raise EdlFsError(f"LocalFS cannot handle {uri!r}")
        return rest if scheme == "file" else uri

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._path(uri))

    def listdir(self, uri: str) -> list[str]:
        path = self._path(uri)
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def upload(self, local: str, uri: str) -> None:
        dst = self._path(uri)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        if os.path.isdir(local):
            # copy into a temp sibling then rename for the same
            # no-partial-dir guarantee checkpoints rely on locally
            tmp = tempfile.mkdtemp(prefix=".edl-up-",
                                   dir=os.path.dirname(dst) or ".")
            try:
                staged = os.path.join(tmp, os.path.basename(dst))
                shutil.copytree(local, staged)
                if os.path.exists(dst):
                    shutil.rmtree(dst)
                os.rename(staged, dst)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        else:
            # temp + rename for single files too: a copy2 interrupted
            # mid-write (ENOSPC, kill) must not leave a truncated dst
            # that presence-based checks then trust (e.g. the sharded-
            # mirror completeness gate keying on index.{r}.json)
            fd, tmp = tempfile.mkstemp(prefix=".edl-up-",
                                       dir=os.path.dirname(dst) or ".")
            os.close(fd)
            try:
                shutil.copy2(local, tmp)
                os.rename(tmp, dst)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)

    def download(self, uri: str, local: str) -> None:
        src = self._path(uri)
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        if os.path.isdir(src):
            if os.path.exists(local):
                shutil.rmtree(local)
            shutil.copytree(src, local)
        else:
            shutil.copy2(src, local)

    def delete(self, uri: str) -> None:
        path = self._path(uri)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)


class CommandFS(FileSystem):
    """Storage-CLI-backed FS (gsutil / hdfs dfs / custom).

    Args map operation -> argv template; "{src}", "{dst}", "{uri}" are
    substituted. `list_cmd` must print one child URI or basename per
    line. A non-zero exit from exists/list is treated as "absent"; from
    transfer ops it raises.
    """

    def __init__(self, *, exists_cmd: Sequence[str], list_cmd: Sequence[str],
                 upload_cmd: Sequence[str], download_cmd: Sequence[str],
                 delete_cmd: Sequence[str]):
        self.cmds = {"exists": list(exists_cmd), "list": list(list_cmd),
                     "upload": list(upload_cmd),
                     "download": list(download_cmd),
                     "delete": list(delete_cmd)}

    def _run(self, op: str, check: bool, **subs: str
             ) -> subprocess.CompletedProcess:
        argv = [a.format(**subs) for a in self.cmds[op]]
        proc = subprocess.run(argv, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise EdlFsError(
                f"{op} failed ({' '.join(argv)}): {proc.stderr.strip()}")
        return proc

    def exists(self, uri: str) -> bool:
        return self._run("exists", check=False, uri=uri).returncode == 0

    def listdir(self, uri: str) -> list[str]:
        proc = self._run("list", check=False, uri=uri)
        if proc.returncode != 0:
            return []
        names = []
        for line in proc.stdout.splitlines():
            line = line.strip().rstrip("/")
            if line:
                names.append(line.rsplit("/", 1)[-1])
        return sorted(set(names))

    def upload(self, local: str, uri: str) -> None:
        self._run("upload", check=True, src=local, dst=uri)

    def download(self, uri: str, local: str) -> None:
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        self._run("download", check=True, src=uri, dst=local)

    def delete(self, uri: str) -> None:
        self._run("delete", check=False, uri=uri)


def gcs_fs() -> CommandFS:
    """gs:// via gsutil (present on GKE TPU images)."""
    return CommandFS(
        exists_cmd=["gsutil", "-q", "stat", "{uri}"],
        list_cmd=["gsutil", "ls", "{uri}"],
        upload_cmd=["gsutil", "-m", "cp", "-r", "{src}", "{dst}"],
        download_cmd=["gsutil", "-m", "cp", "-r", "{src}", "{dst}"],
        delete_cmd=["gsutil", "-m", "rm", "-r", "{uri}"])


def hdfs_fs() -> CommandFS:
    """hdfs:// via the hadoop CLI (the reference's remote store,
    distill/utils.py:18)."""
    return CommandFS(
        exists_cmd=["hdfs", "dfs", "-test", "-e", "{uri}"],
        list_cmd=["hdfs", "dfs", "-ls", "-C", "{uri}"],
        upload_cmd=["hdfs", "dfs", "-put", "-f", "{src}", "{dst}"],
        download_cmd=["hdfs", "dfs", "-get", "{src}", "{dst}"],
        delete_cmd=["hdfs", "dfs", "-rm", "-r", "-f", "{uri}"])


_SCHEMES = {"": LocalFS, "file": LocalFS, "gs": gcs_fs, "hdfs": hdfs_fs}


def register_scheme(scheme: str, factory) -> None:
    """Plug in an FS for a URI scheme (tests register fakes here)."""
    _SCHEMES[scheme] = factory


def resolve(uri: str) -> FileSystem:
    scheme, _ = split_scheme(uri)
    try:
        return _SCHEMES[scheme]()
    except KeyError:
        raise EdlFsError(f"no filesystem registered for {scheme!r}://")


def join_uri(base: str, *parts: str) -> str:
    return "/".join([base.rstrip("/")] + [p.strip("/") for p in parts])


# -- checkpoint mirroring ----------------------------------------------------

_LATEST = "LATEST"


def mirror_checkpoint(local_dir: str, version: int, remote_root: str,
                      *, keep: int | None = None) -> None:
    """Upload a sealed `ckpt-{version}` dir, then flip the LATEST marker.

    Marker-last ordering means a reader that trusts LATEST never sees a
    partially uploaded version (the fault_tolerance.md upload contract).
    With `keep`, remote versions below the newest `keep` are deleted
    after the marker flip.
    """
    fs = resolve(remote_root)
    name = f"ckpt-{version}"
    fs.upload(os.path.join(local_dir, name), join_uri(remote_root, name))
    finalize_mirror(remote_root, version, keep=keep)
    log.info("mirrored %s -> %s", name, remote_root)


def mirror_checkpoint_files(version_dir: str, version: int,
                            remote_root: str, files: Sequence[str]) -> None:
    """Upload the named files of a (possibly still pending) version dir
    into the remote `ckpt-{version}` — WITHOUT touching LATEST.

    The sharded-save mirror path for clusters where the local checkpoint
    dir is NOT shared: every process pushes its own chunks + index file
    this way (from its pending dir), and only after all of them are up
    does rank 0 upload meta.json and flip the marker (`finalize_mirror`)
    — marker-last across the whole world, so a cold pod never reassembles
    from an index whose chunks are missing. Uploading only rank 0's local
    dir would mirror only rank 0's chunks.
    """
    fs = resolve(remote_root)
    name = f"ckpt-{version}"
    for fname in files:
        fs.upload(os.path.join(version_dir, fname),
                  join_uri(remote_root, name, fname))


_COMPLETE = "COMPLETE"


def remote_version_complete(remote_root: str, version: int) -> bool:
    """A remote version dir counts as complete once it holds the
    COMPLETE marker `finalize_mirror` writes AFTER all content is up.
    The marker is the ONLY accepted evidence: meta.json presence is
    unsound on CommandFS backends (a killed mid-upload `gsutil cp -r`
    can land meta.json before the payload — file order inside a
    recursive copy is unspecified), and no heuristic can distinguish a
    pre-marker legacy dir from a killed new-format upload. A mirror
    sealed before the marker existed needs a one-time backfill:
    `resolve(root).exists(...)` the content, then
    `fs.write_text(join_uri(root, "ckpt-N", "COMPLETE"), "N")`."""
    fs = resolve(remote_root)
    return fs.exists(join_uri(remote_root, f"ckpt-{version}", _COMPLETE))


def finalize_mirror(remote_root: str, version: int, *,
                    keep: int | None = None) -> None:
    """Seal the remote version (COMPLETE marker) + flip LATEST + GC.

    Both markers are written only after every content file is up:
    COMPLETE makes the version individually fetchable (explicit-version
    restores), LATEST names the newest one. GC retention counts only
    COMPLETE versions — a partial dir left by a failed earlier mirror
    must not occupy a retention slot (that would delete an older
    complete version early); partials older than the newest complete
    `keep` are deleted outright as garbage.
    """
    fs = resolve(remote_root)
    fs.write_text(join_uri(remote_root, f"ckpt-{version}", _COMPLETE),
                  str(version))
    fs.write_text(join_uri(remote_root, _LATEST), str(version))
    if keep is not None:
        versions = remote_versions(remote_root)
        complete = [v for v in versions
                    if remote_version_complete(remote_root, v)]
        cutoff = complete[-keep] if len(complete) >= keep else None
        if cutoff is not None:
            for v in versions:
                if v < cutoff:
                    fs.delete(join_uri(remote_root, f"ckpt-{v}"))


def remote_versions(remote_root: str) -> list[int]:
    fs = resolve(remote_root)
    out = []
    for name in fs.listdir(remote_root):
        if name.startswith("ckpt-") and name[5:].isdigit():
            out.append(int(name[5:]))
    return sorted(out)


def remote_latest_version(remote_root: str) -> int | None:
    """The LATEST-marked version number, without downloading it."""
    fs = resolve(remote_root)
    marker = join_uri(remote_root, _LATEST)
    if not fs.exists(marker):
        return None
    return int(fs.read_text(marker).strip())


def fetch_latest_checkpoint(remote_root: str, local_dir: str,
                            version: int | None = None) -> int | None:
    """Download the LATEST-marked (or a specific sealed) version into
    local_dir; its number, or None when the remote has no checkpoint."""
    fs = resolve(remote_root)
    if version is None:
        marker = join_uri(remote_root, _LATEST)
        if not fs.exists(marker):
            return None
        version = int(fs.read_text(marker).strip())
    elif (version not in remote_versions(remote_root)
          or not remote_version_complete(remote_root, version)):
        # an explicitly requested version must also be COMPLETE — a
        # partial dir from a failed mirror would download but then
        # crash the restore on its missing meta.json
        return None
    name = f"ckpt-{version}"
    dst = os.path.join(local_dir, name)
    if os.path.isdir(dst):
        return version  # already local (e.g. the surviving pod)
    os.makedirs(local_dir, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".tmp-fetch-", dir=local_dir)
    try:
        staged = os.path.join(tmp, name)
        fs.download(join_uri(remote_root, name), staged)
        try:
            os.rename(staged, dst)
        except OSError:
            if not os.path.isdir(dst):  # lost a concurrent-fetch race: fine
                raise
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    log.info("fetched %s <- %s", name, remote_root)
    return version


def fetch_file(uri: str, cache_dir: str | None = None) -> str:
    """Local path for `uri`: as-is for local paths, else download into
    cache_dir (reference download_hdfs_file, distill/utils.py:18)."""
    scheme, rest = split_scheme(uri)
    if scheme in ("", "file"):
        return rest if scheme == "file" else uri
    cache_dir = cache_dir or os.path.join(
        tempfile.gettempdir(), "edl_tpu_fetch")
    os.makedirs(cache_dir, exist_ok=True)
    dst = os.path.join(cache_dir, rest.replace("/", "_"))
    if not os.path.exists(dst):
        # download-to-temp + rename (same contract as
        # fetch_latest_checkpoint): a CLI killed mid-transfer must not
        # leave a partial file that existence-caching then serves forever
        tmp = tempfile.mkdtemp(prefix=".tmp-fetch-", dir=cache_dir)
        try:
            staged = os.path.join(tmp, "f")
            resolve(uri).download(uri, staged)
            try:
                os.rename(staged, dst)
            except OSError:
                if not os.path.exists(dst):  # concurrent-fetch race: fine
                    raise
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return dst
