"""Network helpers: free ports, host IP, TCP aliveness probe.

Capability parity: reference utils/utils.py (free-port finder, ip helpers),
discovery/server_alive.py:19 (TCP connect probe), pkg/utils/helper.go:24
(GetExternalIP: first non-loopback IPv4).
"""

from __future__ import annotations

import socket


def free_port() -> int:
    """Ask the OS for a currently-free TCP port."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]


def host_ip() -> str:
    """First non-loopback IPv4 of this host; falls back to 127.0.0.1."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            # No packets are sent; this just selects the outbound interface.
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None, socket.AF_INET):
            ip = info[4][0]
            if not ip.startswith("127."):
                return ip
    except OSError:
        pass
    return "127.0.0.1"


def split_endpoint(endpoint: str) -> tuple[str, int]:
    host, port = endpoint.rsplit(":", 1)
    return host, int(port)


def is_endpoint_alive(endpoint: str, timeout: float = 1.0) -> bool:
    """TCP connect probe: True iff something is listening at host:port."""
    host, port = split_endpoint(endpoint)
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False
