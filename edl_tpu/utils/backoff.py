"""Jittered exponential backoff — the one retry cadence for the repo.

Before r17 every reconnecting path rolled its own delay: StoreClient's
connect loop slept a fixed `retry_interval`, ClientWatch re-dialed on a
flat `reconnect_backoff`, and the registry's re-register loop used a
bare 0.5 s wait. Fixed cadences synchronize: when a store leader dies,
every client in the fleet retries on the same beat and the new leader
eats a thundering herd exactly when it is busiest. This helper is the
shared alternative: exponential growth with full jitter (delay drawn
uniformly from [base, current]), reset on success.

Pure stdlib; deterministic when constructed with a seeded ``rng`` (the
selftests do this — wall-clock randomness in a test is a flake).
"""

from __future__ import annotations

import random
import threading


class Backoff:
    """One retry schedule: ``delay()`` returns the next jittered delay
    and advances the window; ``reset()`` on success; ``sleep(stop)``
    combines delay + interruptible wait.

    Not thread-safe by design — each retry loop owns its instance
    (sharing one schedule across threads would couple their cadences,
    which is the herd this class exists to break).
    """

    def __init__(self, base: float = 0.2, factor: float = 2.0,
                 max_delay: float = 5.0,
                 rng: random.Random | None = None):
        self.base = max(1e-3, base)
        self.factor = factor
        self.max_delay = max(self.base, max_delay)
        self._rng = rng or random.Random()
        self._current = self.base

    def delay(self) -> float:
        """Next delay: uniform over [base, current], then grow the
        window (full jitter — AWS-style decorrelation without the
        unbounded tail)."""
        d = self._rng.uniform(self.base, self._current)
        self._current = min(self.max_delay, self._current * self.factor)
        return d

    def reset(self) -> None:
        self._current = self.base

    def sleep(self, stop: threading.Event | None = None) -> bool:
        """Wait out the next delay; True means `stop` fired (caller
        should exit its retry loop, not retry again)."""
        d = self.delay()
        if stop is None:
            threading.Event().wait(d)
            return False
        return stop.wait(d)
