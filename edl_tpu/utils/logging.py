"""Uniform logger factory.

Capability parity: reference utils/utils.py:25-35 (logger with uniform format,
per-component names) and per-trainer log redirection (utils/edl_process.py:70-73,
handled in collective/process.py here).
"""

from __future__ import annotations

import logging
import sys

from edl_tpu.utils import config

_FORMAT = "%(asctime)s %(levelname)s %(name)s [%(process)d] %(message)s"

_configured: set[str] = set()


def get_logger(name: str, level: int | str | None = None) -> logging.Logger:
    """Return a logger with the framework-wide format, configured once."""
    logger = logging.getLogger(name)
    if name not in _configured:
        _configured.add(name)
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
        if level is None:
            level = config.env_str("EDL_TPU_LOG_LEVEL", "INFO")
        logger.setLevel(level)
    return logger
