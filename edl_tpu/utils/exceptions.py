"""Framework exception hierarchy.

Capability parity: reference utils/exceptions.py:16-41 (EdlException subtypes
EdlBarrierError, EdlRegisterError, EdlRankError ...).
"""


class EdlError(Exception):
    """Base class for all edl_tpu errors."""


class EdlStoreError(EdlError):
    """Coordination-store operation failed."""


class EdlRegisterError(EdlError):
    """Could not register (pod rank / service node) in the registry."""


class EdlRankError(EdlError):
    """Rank claim raced out or rank set is inconsistent."""


class EdlBarrierError(EdlError):
    """Barrier timed out or membership changed while waiting."""


class EdlLeaseExpired(EdlStoreError):
    """A lease expired while the owner believed it was alive."""


class EdlDataError(EdlError):
    """Data pipeline / task dispenser error."""


class EdlCheckpointCorrupt(EdlError):
    """A checkpoint chunk failed its integrity check (crc32 recorded at
    seal time, verified on restore — disk and peer paths alike). Typed
    so restore paths can fall back to the previous sealed version or
    another donor instead of loading garbage."""
