"""Typed config with env-var overlay.

The reference's config story is "CLI flag else env var" with a PADDLE_* env
contract parsed ad-hoc in every entrypoint (reference utils/edl_env.py:86-126,
collective/launch.py:47-108). Here the same layering is a single reusable
mechanism: dataclass fields declare an ``env`` name in metadata; ``from_env``
builds the config as defaults < env < explicit kwargs, with values parsed by
the field's declared type.
"""

from __future__ import annotations

import dataclasses
import os
import types
import typing
from typing import Any, TypeVar

T = TypeVar("T")

# --------------------------------------------------------------------------
# The central EDL_TPU_* knob registry.
#
# Single source of truth for every environment variable the package
# reads: a knob exists iff it has a row here, a row in the doc/usage.md
# env reference table, and at least one live read (a `field(env=...)`
# declaration or an `env_*` helper call).  All three are machine-checked
# by `python -m edl_tpu.analysis lint` (the env-registry checker), so
# source<->doc drift fails CI instead of accumulating — the reference
# shipped ~70 ad-hoc PADDLE_* reads against a doc page covering a
# fraction of them, and this repo was on the same trajectory.
#
# Direct `os.environ` reads of EDL_TPU_* names outside this module are
# lint findings; use env_str/env_int/env_float/env_flag/env_present or
# `field(env=...)`.

ENV_VARS: dict[str, str] = {
    # -- identity / membership (launcher -> trainer contract) --------------
    "EDL_TPU_JOB_ID": "job identifier shared by every pod of one job",
    "EDL_TPU_POD_ID": "this pod's unique id within the job",
    "EDL_TPU_RANK": "trainer rank within the elastic world",
    "EDL_TPU_WORLD_SIZE": "elastic world size (launcher pod count)",
    "EDL_TPU_COORDINATOR": "jax distributed coordinator endpoint",
    "EDL_TPU_CLUSTER_JSON": "serialized Cluster doc handed to trainers",
    "EDL_TPU_CLUSTER_VERSION": "cluster generation the trainer launched into",
    "EDL_TPU_STORE_ENDPOINTS": "coordination store endpoints: replicas "
                               "comma-joined, shard groups ;-separated",
    "EDL_TPU_STORE_ELECTION_TTL": "store replica quorum-lease TTL seconds "
                                  "(the failover detection horizon)",
    "EDL_TPU_STORE_FAILOVER_BACKOFF": "client failover backoff base seconds "
                                      "(jittered-exponential)",
    "EDL_TPU_STORE_SHARDS": "shard-group count when splitting a flat "
                            "replica list",
    "EDL_TPU_STORE_REDIRECT_HOPS": "bound on hinted NOT_LEADER/REDIRECT "
                                   "hops before erroring",
    "EDL_TPU_NODES_RANGE": "elastic node range 'min:max'",
    "EDL_TPU_NPROC_PERNODE": "trainer processes per node (0 = auto)",
    "EDL_TPU_UP_LIMIT_NODES": "hard ceiling on world growth",
    "EDL_TPU_JOBSERVER": "JobServer endpoint for resize control",
    "EDL_TPU_SLICES": "multi-slice topology: number of slices",
    "EDL_TPU_SLICE_ID": "this trainer's slice index (rank-contiguous)",
    # -- barriers / leases / rejoin ----------------------------------------
    "EDL_TPU_LEASE_TTL": "store lease TTL seconds for pod claims",
    "EDL_TPU_BARRIER_STABLE": "seconds membership must hold still to pass "
                              "the elastic barrier",
    "EDL_TPU_BARRIER_TIMEOUT": "elastic barrier hard timeout seconds",
    "EDL_TPU_REJOIN_DELAY": "pod rejoin backoff seconds after a kick",
    # -- checkpoint plane ---------------------------------------------------
    "EDL_TPU_CHECKPOINT_PATH": "checkpoint directory root",
    "EDL_TPU_CHECKPOINT_KEEP": "sealed checkpoint versions to retain",
    "EDL_TPU_CHECKPOINT_SHARDED": "per-process sharded checkpoint format",
    "EDL_TPU_CKPT_REMOTE": "remote mirror URI (gs:// / hdfs:// / file://)",
    "EDL_TPU_CKPT_ASYNC": "async snapshot-then-write saves (0 = sync)",
    "EDL_TPU_CKPT_STEPS": "save every N steps (0 = per-epoch only)",
    "EDL_TPU_SAVE_CHECKPOINT_STEPS": "alias of EDL_TPU_CKPT_STEPS "
                                     "(reference env-name parity)",
    "EDL_TPU_SAVE_CHECKPOINT_INTER": "save every N epochs",
    "EDL_TPU_CKPT_RESTORE_THREADS": "parallel restore read threads",
    "EDL_TPU_CKPT_VERIFY": "chunk crc32 verification on restore (0 = off)",
    "EDL_TPU_COMPILE_CACHE_DIR": "persistent XLA compilation cache dir",
    # -- p2p live state migration ------------------------------------------
    "EDL_TPU_RESIZE_P2P": "peer-to-peer live state migration (0 = "
                          "stop-resume from disk)",
    "EDL_TPU_DONOR_LINGER": "seconds a released trainer keeps serving its "
                            "sealed snapshot",
    "EDL_TPU_ADOPT_TIMEOUT": "launcher wait for in-place adoption before "
                             "stop-resume",
    # -- reform state machine (multi-host resize without restart) ----------
    "EDL_TPU_REFORM_QUIESCE_S": "reform quiesce-phase deadline seconds "
                                "(step/ckpt drain; stop-resume downgrade)",
    "EDL_TPU_REFORM_MESH_S": "reform mesh-re-formation deadline seconds "
                             "(stop-resume downgrade)",
    "EDL_TPU_REFORM_RESTORE_S": "reform peer/disk restore deadline seconds "
                                "(peer failure downgrades to disk)",
    "EDL_TPU_REFORM_REJIT_S": "reform re-jit + first-step deadline seconds "
                              "(advisory past dispatch; launcher adopt "
                              "timeout is the hard bound)",
    # -- train loop / input plane ------------------------------------------
    "EDL_TPU_NUM_EPOCHS": "epochs to train",
    "EDL_TPU_LOG_EVERY": "log metrics every N steps",
    "EDL_TPU_PREFETCH_BATCHES": "host->device prefetch depth",
    "EDL_TPU_LOADER_WORKERS": "mp input-plane worker processes (0 = inline)",
    "EDL_TPU_AUGMENT_DEVICE": "jitted on-device crop/flip/normalize",
    "EDL_TPU_COMM_BUCKET_MB": "gradient reduction bucket size MiB "
                              "(0 = XLA-partitioned single reduction)",
    "EDL_TPU_DCN_COMPRESS": "cross-slice gradient wire format: "
                            "off | topk | int8 (loss-parity gated)",
    "EDL_TPU_MOE_DISPATCH": "MoE all-to-all decomposition: flat | hier "
                            "(ICI leg + cross-slice DCN leg)",
    "EDL_TPU_MOE_COMPRESS": "MoE dispatch DCN-leg wire format: "
                            "off | int8 (parity-gated)",
    "EDL_TPU_FUSED_OPT": "fused optimizer path: off | fp32 | int8 | fp8 "
                         "(train/fused_opt.py; fp32 is bitwise vs optax, "
                         "int8/fp8 quantize resident moments)",
    "EDL_TPU_OPT_QUANT": "override the resident-moment codec of the "
                         "fused optimizer: off | int8 | fp8 (defaults "
                         "to what EDL_TPU_FUSED_OPT implies)",
    "EDL_TPU_DISTILL_NOP": "distill reader no-op mode (wire debugging)",
    # -- logging / profiling ------------------------------------------------
    "EDL_TPU_LOG_DIR": "launcher workerlog directory",
    "EDL_TPU_LOG_LEVEL": "python log level for edl_tpu loggers",
    "EDL_TPU_PROFILE": "timeline tracing on/off",
    "EDL_TPU_PROFILE_DIR": "jax profiler trace output directory",
    "EDL_TPU_PROFILE_START": "profiler start step",
    "EDL_TPU_PROFILE_STEPS": "profiler step count",
    # -- control plane (watch streams, utilization) ------------------------
    "EDL_TPU_COORD_WATCH": "store watch streams (0 = poll everywhere)",
    "EDL_TPU_WATCH_RESYNC_S": "resync safety-net period for event-driven "
                              "consumers",
    "EDL_TPU_PUBLISH_UTIL": "trainer utilization publishing (0 = off)",
    "EDL_TPU_RELAY_ENDPOINTS": "watch relay tier endpoints (comma-joined); "
                               "when set, StoreClient.watch streams dial "
                               "the relay instead of the store",
    "EDL_TPU_RELAY_BUFFER": "relay per-prefix replay-history length "
                            "(events kept for late/resuming downstreams)",
    "EDL_TPU_LEASE_COALESCE": "host-scoped lease coalescing: one lease + "
                              "one keepalive writer carries all of a "
                              "host's pod registrations (0 = per-pod)",
    # -- autoscaler (trainer worlds) ---------------------------------------
    "EDL_TPU_SCALER_INTERVAL": "fallback decision interval seconds",
    "EDL_TPU_SCALER_MIN_TICK": "floor between event-triggered passes",
    "EDL_TPU_SCALER_COOLDOWN": "per-job resize cooldown seconds",
    "EDL_TPU_SCALER_GAIN": "marginal-gain threshold to grow",
    "EDL_TPU_SCALER_STALENESS": "utilization record staleness bound",
    "EDL_TPU_SCALER_MIN_NODES": "per-job world floor",
    "EDL_TPU_SCALER_MAX_NODES": "per-job world ceiling",
    "EDL_TPU_SCALER_LEADER_TTL": "scaler leader-election lease TTL",
    "EDL_TPU_ELASTIC_DOWNTIME_S": "seed value for the per-resize downtime "
                                  "charge",
    "EDL_TPU_DOWNTIME_ARTIFACT": "bench JSON to seed the downtime charge "
                                 "from",
    # -- serving elasticity (teacher pools) --------------------------------
    "EDL_TPU_SERVE_SLO_P95_MS": "serving latency SLO target (p95, ms)",
    "EDL_TPU_SERVE_QUEUE_HIGH": "queued requests per teacher counting as "
                                "a breach",
    "EDL_TPU_SERVE_SHED_HIGH": "pool-wide shed rate (rejects/sec) "
                               "counting as a breach even at healthy "
                               "p95",
    "EDL_TPU_SERVE_UTIL_LOW": "shrink only under this mean utilization",
    "EDL_TPU_SERVE_SHRINK_HEADROOM": "shrink only with p95 under this "
                                     "fraction of the SLO",
    "EDL_TPU_SERVE_BREACH_TICKS": "consecutive breach ticks before a grow",
    "EDL_TPU_SERVE_IDLE_TICKS": "consecutive idle ticks before a shrink",
    "EDL_TPU_SERVE_COOLDOWN": "serving resize cooldown seconds",
    "EDL_TPU_SERVE_GROW_FACTOR": "multiplicative grow cap",
    "EDL_TPU_SERVE_MIN_TEACHERS": "pool floor",
    "EDL_TPU_SERVE_MAX_TEACHERS": "pool ceiling",
    "EDL_TPU_SERVE_DRAIN_DEADLINE": "graceful-drain budget before "
                                    "hard-kill",
    "EDL_TPU_SERVE_BATCHING": "teacher batch admission mode: continuous "
                              "(iteration-level) or window (r6 coalesce)",
    "EDL_TPU_SERVE_ADMIT_CAP": "bounded per-(tenant, class) teacher "
                               "queue; past it submits reject with "
                               "retry-after",
    "EDL_TPU_SERVE_CLASS_WEIGHTS": "WFQ weights per priority class, "
                                   "e.g. high=4,normal=2,low=1 (also "
                                   "scales shed delay budgets)",
    "EDL_TPU_SERVE_SHED_MS": "normal-class queue-delay budget (ms) for "
                             "overload shedding; <=0 disables the "
                             "delay-based shed rule",
    "EDL_TPU_SERVE_RETRY_BUDGET": "reader-side bounded retry budget per "
                                  "task on teacher shed responses",
    # -- fleet simulator / preemptive scheduler ----------------------------
    "EDL_TPU_FLEET_JOBS": "fleet tournament: concurrent trainer jobs "
                          "per generated trace",
    "EDL_TPU_FLEET_POOLS": "fleet tournament: concurrent serving pools "
                           "per generated trace",
    "EDL_TPU_FLEET_TICKS": "fleet tournament: virtual ticks per run",
    "EDL_TPU_FLEET_SPOT_FRACTION": "fleet tournament: fraction of the "
                                   "node budget that is revocable spot "
                                   "capacity",
    "EDL_TPU_SPOT_NOTICE_S": "spot preemption notice window seconds a "
                             "noticed worker has to quiesce-seal-donate "
                             "before the hard kill (0 = ignore notices)",
    # -- analysis plane -----------------------------------------------------
    "EDL_TPU_LOCKGRAPH": "lock-order race detector during pytest (1 = on)",
    "EDL_TPU_LOCKGRAPH_OUT": "lockgraph JSON report path",
    # -- chaos plane ---------------------------------------------------------
    "EDL_TPU_WIRE_STALL_S": "mid-frame wire stall deadline seconds "
                            "(<=0 disables)",
    # -- observability plane -------------------------------------------------
    "EDL_TPU_METRICS_PORT": "Prometheus-text scrape endpoint port "
                            "(0/unset = off)",
    "EDL_TPU_TRACE": "causal span tracing: 1 = on (sink ./edl_trace), "
                     "a path = on with that sink dir, 0/unset = off",
    "EDL_TPU_FLIGHT_RECORDER_N": "flight-recorder ring capacity per "
                                 "process (0 = off)",
}


def _declared(name: str) -> str:
    if name not in ENV_VARS:
        raise KeyError(
            f"{name} is not declared in edl_tpu.utils.config.ENV_VARS — "
            "add a declaration (and a doc/usage.md row); "
            "'python -m edl_tpu.analysis lint' enforces this")
    return name


def env_str(name: str, default: str | None = None) -> str | None:
    """Read a declared knob as a string (None/default when unset)."""
    value = os.environ.get(_declared(name))
    return default if value is None or value == "" else value


def env_int(name: str, default: int = 0) -> int:
    value = os.environ.get(_declared(name), "").strip()
    try:
        return int(value) if value else default
    except ValueError:
        return default


def env_float(name: str, default: float = 0.0) -> float:
    value = os.environ.get(_declared(name), "").strip()
    try:
        return float(value) if value else default
    except ValueError:
        return default


def env_flag(name: str, default: bool = False) -> bool:
    """Truthy env parse ('1'/'true'/'yes'/'on'), same grammar as
    `from_env`'s bool fields."""
    value = os.environ.get(_declared(name))
    if value is None:
        return default
    return value.lower() in ("1", "true", "yes", "on")


def env_present(name: str) -> bool:
    """Is the declared knob set at all (the 'under the launcher?' probe)."""
    return _declared(name) in os.environ


def field(default: Any = dataclasses.MISSING, *,
          env: str | tuple[str, ...] | None = None, **kw):
    """Dataclass field that can be overridden by the env var ``env`` (a
    tuple names aliases — first one set wins)."""
    metadata = dict(kw.pop("metadata", {}))
    if env is not None:
        metadata["env"] = env
    if default is not dataclasses.MISSING and not kw.get("default_factory"):
        kw["default"] = default
    return dataclasses.field(metadata=metadata, **kw)


def _parse(value: str, typ: Any) -> Any:
    origin = typing.get_origin(typ)
    if origin is typing.Union or origin is types.UnionType:  # Optional[X] / X | None
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if not value:
            return None
        return _parse(value, args[0])
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ in (int, float, str):
        return typ(value)
    if origin in (list, tuple):
        (elem,) = typing.get_args(typ)[:1] or (str,)
        items = [_parse(v.strip(), elem) for v in value.split(",") if v.strip()]
        return tuple(items) if origin is tuple else items
    return value


def from_env(cls: type[T], **overrides: Any) -> T:
    """Build ``cls`` with env-var overlay: defaults < env < overrides."""
    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        env_name = f.metadata.get("env")
        names = (env_name,) if isinstance(env_name, str) else (env_name or ())
        for name in names:
            if name.startswith("EDL_TPU_"):
                _declared(name)   # typo'd knobs fail loudly, not silently
            if name in os.environ:
                kwargs[f.name] = _parse(os.environ[name],
                                        hints.get(f.name, str))
                break
    kwargs.update(overrides)
    return cls(**kwargs)


def describe(cfg: Any) -> str:
    """Pretty one-per-line dump (reference train_with_fleet.py print_arguments)."""
    lines = [f"----------- {type(cfg).__name__} -----------"]
    for f in dataclasses.fields(cfg):
        lines.append(f"{f.name}: {getattr(cfg, f.name)}")
    lines.append("------------------------------------------")
    return "\n".join(lines)
