"""Typed config with env-var overlay.

The reference's config story is "CLI flag else env var" with a PADDLE_* env
contract parsed ad-hoc in every entrypoint (reference utils/edl_env.py:86-126,
collective/launch.py:47-108). Here the same layering is a single reusable
mechanism: dataclass fields declare an ``env`` name in metadata; ``from_env``
builds the config as defaults < env < explicit kwargs, with values parsed by
the field's declared type.
"""

from __future__ import annotations

import dataclasses
import os
import types
import typing
from typing import Any, TypeVar

T = TypeVar("T")


def field(default: Any = dataclasses.MISSING, *,
          env: str | tuple[str, ...] | None = None, **kw):
    """Dataclass field that can be overridden by the env var ``env`` (a
    tuple names aliases — first one set wins)."""
    metadata = dict(kw.pop("metadata", {}))
    if env is not None:
        metadata["env"] = env
    if default is not dataclasses.MISSING and not kw.get("default_factory"):
        kw["default"] = default
    return dataclasses.field(metadata=metadata, **kw)


def _parse(value: str, typ: Any) -> Any:
    origin = typing.get_origin(typ)
    if origin is typing.Union or origin is types.UnionType:  # Optional[X] / X | None
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if not value:
            return None
        return _parse(value, args[0])
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ in (int, float, str):
        return typ(value)
    if origin in (list, tuple):
        (elem,) = typing.get_args(typ)[:1] or (str,)
        items = [_parse(v.strip(), elem) for v in value.split(",") if v.strip()]
        return tuple(items) if origin is tuple else items
    return value


def from_env(cls: type[T], **overrides: Any) -> T:
    """Build ``cls`` with env-var overlay: defaults < env < overrides."""
    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        env_name = f.metadata.get("env")
        names = (env_name,) if isinstance(env_name, str) else (env_name or ())
        for name in names:
            if name in os.environ:
                kwargs[f.name] = _parse(os.environ[name],
                                        hints.get(f.name, str))
                break
    kwargs.update(overrides)
    return cls(**kwargs)


def describe(cfg: Any) -> str:
    """Pretty one-per-line dump (reference train_with_fleet.py print_arguments)."""
    lines = [f"----------- {type(cfg).__name__} -----------"]
    for f in dataclasses.fields(cfg):
        lines.append(f"{f.name}: {getattr(cfg, f.name)}")
    lines.append("------------------------------------------")
    return "\n".join(lines)
