from edl_tpu.utils.logging import get_logger
from edl_tpu.utils.net import free_port, host_ip, is_endpoint_alive
from edl_tpu.utils.exceptions import (
    EdlError,
    EdlBarrierError,
    EdlRankError,
    EdlRegisterError,
    EdlStoreError,
)

__all__ = [
    "get_logger",
    "free_port",
    "host_ip",
    "is_endpoint_alive",
    "EdlError",
    "EdlBarrierError",
    "EdlRankError",
    "EdlRegisterError",
    "EdlStoreError",
]
