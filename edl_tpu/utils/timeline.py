"""Env-gated per-stage host timeline profiler — a shim over the obs
plane since the observability PR.

Capability of the reference's distill timeline (distill/timeline.py:20-43:
``DISTILL_READER_PROFILE=1`` swaps a nop for a real recorder emitting
``pid/op/ms`` lines to stderr, hooked at every pipeline stage). Ours is
``EDL_TPU_PROFILE=1`` and also offers a jax-profiler trace context for
device-side timelines.

    tl = timeline("distill.worker")      # nop unless profiling/tracing
    with tl.span("predict"):
        ...
    tl.record("put_data", t0)            # explicit start time

Sinks (the r19 hot-path fix — the old ``_RealTimeline.record`` did an
UNBUFFERED per-event ``print`` to stderr, a measurable syscall tax on
the distill reader's per-batch path):

- obs span plane: with ``EDL_TPU_TRACE`` on, every timeline op becomes
  a finished span in the process's trace sink (merged/viewed by
  ``python -m edl_tpu.obs trace``), parented onto whatever span is
  current — a ckpt write inside a resize trace lands inside the trace;
- flight recorder ring: every op is an always-on bounded ring event
  (``obs/recorder.py``) so a crash dump shows the last operations;
- stderr (``EDL_TPU_PROFILE=1``, the back-compat sink selection): the
  same ``timeline pid=... op ms`` lines, now BATCHED through a small
  buffer flushed every `_FLUSH_EVERY` lines and at exit.
"""

from __future__ import annotations

import atexit
import contextlib
import os
import sys
import threading
import time

from edl_tpu.obs import recorder as _flight
from edl_tpu.obs import trace as _trace
from edl_tpu.utils import config


class _NopTimeline:
    __slots__ = ()

    def span(self, op: str):
        return contextlib.nullcontext()

    def record(self, op: str, start: float) -> None:
        pass

    enabled = False


# -- buffered stderr sink (EDL_TPU_PROFILE=1) -------------------------------

_FLUSH_EVERY = 64
_buf_lock = threading.Lock()
_buf: list[str] = []         # guarded-by: _buf_lock
_atexit_armed = False        # guarded-by: _buf_lock


def _flush_stderr() -> None:
    with _buf_lock:
        lines, _buf[:] = list(_buf), []
    if lines:
        try:
            sys.stderr.write("\n".join(lines) + "\n")
            sys.stderr.flush()
        except (OSError, ValueError):
            pass


def _stderr_line(line: str) -> None:
    global _atexit_armed
    flush = False
    with _buf_lock:
        _buf.append(line)
        if not _atexit_armed:
            _atexit_armed = True
            atexit.register(_flush_stderr)
        flush = len(_buf) >= _FLUSH_EVERY
    if flush:
        _flush_stderr()


class _ObsTimeline:
    """Real timeline: routes every op into the obs planes (see module
    docstring). Construction is gated, so the hot path of a process
    with neither knob set stays the zero-cost nop."""

    __slots__ = ("name", "_stderr")
    enabled = True

    def __init__(self, name: str):
        self.name = name
        self._stderr = profiling_enabled()

    @contextlib.contextmanager
    def span(self, op: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.record(op, t0)

    def record(self, op: str, start: float) -> None:
        dur_s = time.monotonic() - start
        full = f"{self.name}.{op}"
        _trace.event(full, dur_s)   # span plane (no-op when trace off)
        _flight.record("timeline", op=full, ms=round(dur_s * 1e3, 3))
        if self._stderr:
            _stderr_line(f"timeline pid={os.getpid()} {full} "
                         f"{dur_s * 1e3:.3f}ms")


def profiling_enabled() -> bool:
    return config.env_flag("EDL_TPU_PROFILE", False)


def timeline(name: str):
    """Nop unless EDL_TPU_PROFILE=1 or EDL_TPU_TRACE is on (zero
    overhead on the hot path either way — the nop is attribute-free,
    and the real sink batches instead of printing per event)."""
    if profiling_enabled() or _trace.enabled():
        return _ObsTimeline(name)
    return _NopTimeline()


@contextlib.contextmanager
def device_trace(logdir: str):
    """jax profiler trace (TensorBoard-viewable) around a code region —
    the device-side analogue of the reference's --profile batches window
    (train_with_fleet.py:521-530)."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
