"""Env-gated per-stage host timeline profiler.

Capability of the reference's distill timeline (distill/timeline.py:20-43:
``DISTILL_READER_PROFILE=1`` swaps a nop for a real recorder emitting
``pid/op/ms`` lines to stderr, hooked at every pipeline stage). Ours is
``EDL_TPU_PROFILE=1`` and also offers a jax-profiler trace context for
device-side timelines.

    tl = timeline("distill.worker")      # nop unless EDL_TPU_PROFILE=1
    with tl.span("predict"):
        ...
    tl.record("put_data", t0)            # explicit start time
"""

from __future__ import annotations

import contextlib
import os
import sys
import time

from edl_tpu.utils import config


class _NopTimeline:
    __slots__ = ()

    def span(self, op: str):
        return contextlib.nullcontext()

    def record(self, op: str, start: float) -> None:
        pass

    enabled = False


class _RealTimeline:
    __slots__ = ("name",)
    enabled = True

    def __init__(self, name: str):
        self.name = name

    @contextlib.contextmanager
    def span(self, op: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.record(op, t0)

    def record(self, op: str, start: float) -> None:
        ms = (time.monotonic() - start) * 1000.0
        print(f"timeline pid={os.getpid()} {self.name}.{op} {ms:.3f}ms",
              file=sys.stderr, flush=True)


def profiling_enabled() -> bool:
    return config.env_flag("EDL_TPU_PROFILE", False)


def timeline(name: str):
    """Nop unless EDL_TPU_PROFILE=1 (zero overhead on the hot path)."""
    return _RealTimeline(name) if profiling_enabled() else _NopTimeline()


@contextlib.contextmanager
def device_trace(logdir: str):
    """jax profiler trace (TensorBoard-viewable) around a code region —
    the device-side analogue of the reference's --profile batches window
    (train_with_fleet.py:521-530)."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
