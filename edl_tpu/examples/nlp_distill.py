"""NLP distillation: big-teacher -> BOW student over the distill plane.

Capability of the reference's ERNIE->BOW pipeline (example/distill/nlp/:
fine_tune.py trains the big teacher and exports it to serving; distill.py
trains a small BOW/CNN student against served teacher logits mixed with
hard labels, model.py:84-135), tpu-native end to end: the teacher is a
jitted CNN text classifier fine-tuned in-process (the ERNIE stand-in),
served through `TeacherServer` + consumed through `DistillReader`'s
exactly-once pipeline; the student is the BOW model distilling with
temperature-T KL + hard-label CE (distill.py:96-107's loss).

Reported at the end, matching the reference's README table: teacher acc,
student-alone acc (train from scratch, no teacher), distilled student acc.

Modes (same shape as mnist_distill):
  --all-in-one          in-process teacher — no external services;
  --teachers h:p,...    fixed endpoints (teacher_server CLI instances);
  --discovery h:p       dynamic discovery via the balancer daemon.

Data is synthetic sentiment (deterministic, no downloads): each sequence
is token ids where the label is decided by whether more ids fall in the
"positive" or "negative" vocabulary band, plus neutral noise — BOW-
learnable, but noisy enough that the bigger teacher generalizes better.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.data.pipeline import ArraySource, DataLoader
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.models.bow import BOWClassifier, CNNClassifier
from edl_tpu.train.classification import (create_state, make_distill_step,
                                          make_eval_step)
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.nlp_distill")

VOCAB = 4000
SEQ_LEN = 64
NUM_CLASSES = 2
POS_BAND = (100, 400)   # ids voting positive
NEG_BAND = (400, 700)   # ids voting negative


def synthetic_sentiment(n: int, seed: int = 0, noise: float = 0.15):
    """(ids (n, SEQ_LEN) int32, label (n,) int32) — band-vote labels."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, VOCAB, size=(n, SEQ_LEN)).astype(np.int32)
    # random pad tail (id 0) so masking matters
    lengths = rng.integers(SEQ_LEN // 2, SEQ_LEN + 1, size=n)
    for i, ln in enumerate(lengths):
        ids[i, ln:] = 0
    pos = ((ids >= POS_BAND[0]) & (ids < POS_BAND[1])).sum(axis=1)
    neg = ((ids >= NEG_BAND[0]) & (ids < NEG_BAND[1])).sum(axis=1)
    label = (pos + rng.normal(scale=noise * SEQ_LEN ** 0.5, size=n)
             > neg).astype(np.int32)
    return {"ids": ids, "label": label}


def _fit(model, data, *, epochs: int, batch_size: int, lr: float, seed: int,
         step_builder):
    """Plain supervised fit; returns the trained state."""
    state = create_state(model, jax.random.PRNGKey(seed), (1, SEQ_LEN),
                         optax.adam(lr), input_dtype=jnp.int32)
    loader = DataLoader(ArraySource(data), batch_size, seed=seed)
    step = step_builder()
    for epoch in range(epochs):
        for batch in loader.epoch(epoch):
            state, _ = step(state, {"ids": jnp.asarray(batch["ids"]),
                                    "label": jnp.asarray(batch["label"])})
    return state


def _acc(state, data, eval_step) -> float:
    ev = eval_step(state, {"ids": jnp.asarray(data["ids"]),
                           "label": jnp.asarray(data["label"])})
    return float(ev["acc1"])


def train(args) -> int:
    train_data = synthetic_sentiment(args.samples, seed=args.seed)
    test_data = synthetic_sentiment(args.samples // 4, seed=args.seed + 1)
    eval_step = make_eval_step(input_key="ids")

    # -- teacher: "fine-tune the big model" (fine_tune.py analogue) --------
    teacher_model = CNNClassifier(vocab_size=VOCAB, embed_dim=128,
                                  num_classes=NUM_CLASSES)
    server = None
    teachers = None
    if args.all_in_one:
        log.info("fine-tuning the teacher (CNN) in-process...")
        # The teacher's edge is the ERNIE story: it was trained on much
        # more data than the labeled set the students get (the stand-in
        # for pretraining) — so its soft labels carry signal the small
        # train set alone doesn't.
        teacher_data = synthetic_sentiment(args.samples * 4,
                                           seed=args.seed + 7)
        tstate = _fit(teacher_model, teacher_data,
                      epochs=args.teacher_epochs,
                      batch_size=args.batch_size, lr=args.lr, seed=args.seed,
                      step_builder=lambda: _pure_ce_step())
        teacher_acc = _acc(tstate, test_data, eval_step)

        @jax.jit
        def tforward(ids):
            return teacher_model.apply({"params": tstate.params}, ids,
                                       train=False)

        def predict(feeds):
            return {"teacher_logits":
                    np.asarray(tforward(jnp.asarray(feeds["ids"])),
                               np.float32)}

        server = TeacherServer(predict, host="127.0.0.1",
                               max_batch=args.teacher_batch_size * 4).start()
        teachers = [f"127.0.0.1:{server.port}"]
    else:
        teacher_acc = float("nan")
        if args.teachers:
            teachers = args.teachers.split(",")

    # -- student baseline: train-from-scratch BOW (train.py analogue) ------
    student_model = BOWClassifier(vocab_size=VOCAB, embed_dim=args.embed_dim,
                                  num_classes=NUM_CLASSES)
    alone = _fit(student_model, train_data, epochs=args.epochs,
                 batch_size=args.batch_size, lr=args.lr, seed=args.seed,
                 step_builder=lambda: _pure_ce_step())
    alone_acc = _acc(alone, test_data, eval_step)

    # -- distilled student (distill.py analogue) ---------------------------
    # The student distills over the labeled set PLUS unlabeled text the
    # teacher soft-labels on the fly (--distill-extra; the transfer-set
    # trick — with hard_weight=0 those extra rows contribute teacher
    # signal only, their synthetic labels are never in the loss).
    if args.distill_extra:
        extra = synthetic_sentiment(args.distill_extra, seed=args.seed + 11)
        distill_data = {k: np.concatenate([train_data[k], extra[k]])
                        for k in train_data}
    else:
        distill_data = train_data
    loader = DataLoader(ArraySource(distill_data), args.batch_size,
                        seed=args.seed)
    state = create_state(student_model, jax.random.PRNGKey(args.seed),
                         (1, SEQ_LEN), optax.adam(args.lr),
                         input_dtype=jnp.int32)
    step = make_distill_step(NUM_CLASSES, temperature=args.temperature,
                             hard_weight=args.hard_weight, input_key="ids")
    try:
        for epoch in range(args.epochs):
            dr = DistillReader(
                lambda e=epoch: loader.epoch(e), feeds=["ids"],
                predicts=["teacher_logits"], teachers=teachers,
                discovery=args.discovery or None, service=args.service,
                teacher_batch_size=args.teacher_batch_size)
            losses = []
            for batch in dr():
                state, metrics = step(state, batch)
                # device scalar — float() here would sync every step and
                # serialize training against the async reader pipeline
                losses.append(metrics["loss"])
            dr.close()
            losses = [float(l) for l in losses]
            log.info("epoch %d distill loss=%.4f student_acc=%.3f", epoch,
                     float(np.mean(losses)), _acc(state, test_data,
                                                  eval_step))
        distilled_acc = _acc(state, test_data, eval_step)
        log.info("teacher=%.3f student_alone=%.3f student_distilled=%.3f",
                 teacher_acc, alone_acc, distilled_acc)
        print(f"teacher_acc={teacher_acc:.3f} alone_acc={alone_acc:.3f} "
              f"distill_acc={distilled_acc:.3f}")
        return 0
    finally:
        if server is not None:
            server.stop()


def _pure_ce_step():
    """CE-only step over {'ids','label'} batches (teacher-free fit)."""
    from edl_tpu.train.classification import (accuracy_topk,
                                              smoothed_labels,
                                              soft_cross_entropy)
    from edl_tpu.train.step import make_train_step

    def loss_fn(state, params, batch):
        logits = state.apply_fn({"params": params}, batch["ids"], train=True)
        loss = soft_cross_entropy(
            logits, smoothed_labels(batch["label"], NUM_CLASSES))
        return loss, {"acc1": accuracy_topk(logits, batch["label"], 1)}

    return make_train_step(loss_fn, donate=False)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl_tpu.examples.nlp_distill")
    parser.add_argument("--all-in-one", action="store_true")
    parser.add_argument("--teachers", default="")
    parser.add_argument("--discovery", default="")
    parser.add_argument("--service", default="nlp_teacher")
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--teacher-epochs", type=int, default=4)
    parser.add_argument("--samples", type=int, default=4096)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--teacher-batch-size", type=int, default=32)
    parser.add_argument("--embed-dim", type=int, default=32)
    parser.add_argument("--distill-extra", type=int, default=None,
                        help="unlabeled rows the teacher soft-labels "
                             "(default 3x --samples)")
    parser.add_argument("--temperature", type=float, default=2.0)
    parser.add_argument("--hard-weight", type=float, default=0.0)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    if args.distill_extra is None:
        args.distill_extra = args.samples * 3
    if not (args.all_in_one or args.teachers or args.discovery):
        parser.error("pick --all-in-one, --teachers or --discovery")
    return train(args)


if __name__ == "__main__":
    sys.exit(main())
